#!/usr/bin/env python3
"""Image classification on RAW generated stubs (reference
grpc_image_client.py, 501 LoC — same app as image_client.py but built
directly on service_pb2 messages, no client library):

* fetches ModelMetadata/ModelConfig pb and validates a 1-in/1-out image
  model (parse_model, reference :81-168),
* preprocesses a PIL or synthetic image (reference :171-210),
* packs the tensor into ``raw_input_contents`` and requests top-k
  classification via the ``classification`` output parameter
  (reference :278),
* unpacks "score:index[:label]" BYTES strings from ``raw_output_contents``
  (reference postprocess :213-243).

Without an image argument it classifies a synthetic image and prints PASS.
"""

import argparse
import struct
import sys

import grpc
import numpy as np

from _raw_stub import generate_stubs, rpc
from triton_client_tpu.utils import (
    deserialize_bytes_tensor,
    triton_to_np_dtype,
)


def parse_model(meta, config):
    """Validate 1-in/1-out image model from pb metadata+config (reference
    grpc_image_client.py:81-168); returns (input, output, c, h, w, layout,
    dtype, max_batch)."""
    if len(meta.inputs) != 1:
        raise Exception(f"expecting 1 input, got {len(meta.inputs)}")
    if len(meta.outputs) != 1:
        raise Exception(f"expecting 1 output, got {len(meta.outputs)}")
    input_meta = meta.inputs[0]
    output_meta = meta.outputs[0]
    max_batch_size = config.config.max_batch_size

    shape = list(input_meta.shape)
    if max_batch_size > 0:
        shape = shape[1:]
    if len(shape) != 3:
        raise Exception(f"expecting input rank 3, got {shape}")
    if shape[0] in (1, 3):
        layout, (c, h, w) = "CHW", shape
    elif shape[2] in (1, 3):
        layout, (h, w, c) = "HWC", shape
    else:
        raise Exception(f"cannot infer layout from shape {shape}")
    return (input_meta.name, output_meta.name, c, h, w, layout,
            input_meta.datatype, max_batch_size)


def preprocess(img, layout, dtype, c, h, w, scaling):
    """PIL image -> network-ready ndarray (reference :171-210)."""
    if c == 1:
        img = img.convert("L")
    else:
        img = img.convert("RGB")
    img = img.resize((w, h))
    npdtype = triton_to_np_dtype(dtype)
    typed = np.array(img).astype(npdtype)
    if c == 1:
        typed = typed[:, :, np.newaxis]
    if scaling == "INCEPTION":
        scaled = (typed / 127.5) - 1
    elif scaling == "VGG":
        if c == 1:
            scaled = typed - 128
        else:
            scaled = typed - np.asarray((123, 117, 104), dtype=npdtype)
    else:
        scaled = typed
    if layout == "CHW":
        scaled = np.transpose(scaled, (2, 0, 1))
    return scaled.astype(npdtype)


def synthetic_batch(c, h, w, layout, dtype, batch):
    npdtype = triton_to_np_dtype(dtype)
    rng = np.random.default_rng(20240101)
    shape = (c, h, w) if layout == "CHW" else (h, w, c)
    return [rng.standard_normal(shape).astype(npdtype) for _ in range(batch)]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("image_filename", nargs="?", default=None)
    ap.add_argument("-m", "--model-name", default="simple_cnn")
    ap.add_argument("-x", "--model-version", default="")
    ap.add_argument("-b", "--batch-size", type=int, default=1)
    ap.add_argument("-c", "--classes", type=int, default=3)
    ap.add_argument("-s", "--scaling", default="NONE",
                    choices=["NONE", "INCEPTION", "VGG"])
    ap.add_argument("-u", "--url", default="localhost:8001")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    pb = generate_stubs()
    channel = grpc.insecure_channel(args.url)

    meta = rpc(channel, "ModelMetadata",
               pb.ModelMetadataRequest(name=args.model_name,
                                       version=args.model_version),
               pb.ModelMetadataResponse)
    config = rpc(channel, "ModelConfig",
                 pb.ModelConfigRequest(name=args.model_name,
                                       version=args.model_version),
                 pb.ModelConfigResponse)
    (input_name, output_name, c, h, w, layout, dtype,
     max_batch) = parse_model(meta, config)

    if args.image_filename:
        from PIL import Image
        img = Image.open(args.image_filename)
        images = [preprocess(img, layout, dtype, c, h, w, args.scaling)
                  for _ in range(args.batch_size)]
    else:
        images = synthetic_batch(c, h, w, layout, dtype, args.batch_size)

    batched = np.stack(images, axis=0)
    if max_batch == 0:
        batched = batched[0]

    req = pb.ModelInferRequest(model_name=args.model_name,
                               model_version=args.model_version)
    t = req.inputs.add()
    t.name = input_name
    t.datatype = dtype
    t.shape.extend(list(batched.shape))
    req.raw_input_contents.append(batched.tobytes())
    out = req.outputs.add()
    out.name = output_name
    out.parameters["classification"].int64_param = args.classes

    resp = rpc(channel, "ModelInfer", req, pb.ModelInferResponse)
    if len(resp.raw_output_contents) != 1:
        sys.exit(f"expected 1 output, got {len(resp.raw_output_contents)}")
    results = deserialize_bytes_tensor(resp.raw_output_contents[0])
    results = results.reshape(-1, args.classes) if max_batch > 0 else \
        results.reshape(1, args.classes)

    for b, row in enumerate(results):
        print(f"Image {b}:")
        for cls in row:
            s = cls.decode()
            print(f"    {s}")
            score = float(s.split(":")[0])
            if not np.isfinite(score):
                sys.exit("error: non-finite classification score")
    if results.shape[0] != (args.batch_size if max_batch > 0 else 1):
        sys.exit("error: wrong result count")
    print("PASS: grpc_image_client")


if __name__ == "__main__":
    main()
