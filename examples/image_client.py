#!/usr/bin/env python3
"""Image classification CLI (reference image_client.py, 550 LoC — the
application-level behavioral spec, SURVEY.md §3.6):

* fetches model metadata+config and validates a 1-input/1-output image model
  (CHW/HWC layout, optional batch dim) — parse_model (:59-150),
* preprocesses with PIL (resize + INCEPTION/VGG scaling) (:153-192),
* batches, runs sync / async / streaming inference,
* postprocesses classification strings "score:index[:label]" (:195-217).

Without an image argument it classifies a synthetic image, so it doubles as
an executable acceptance test (prints PASS)."""

import argparse
import queue
import sys
from functools import partial

import numpy as np


def parse_model(model_metadata, model_config):
    """Validate 1-in/1-out image model; return (input name, output name,
    c, h, w, layout, dtype, max_batch)."""
    if len(model_metadata["inputs"]) != 1:
        raise Exception(f"expecting 1 input, got {len(model_metadata['inputs'])}")
    if len(model_metadata["outputs"]) != 1:
        raise Exception(f"expecting 1 output, got {len(model_metadata['outputs'])}")
    input_metadata = model_metadata["inputs"][0]
    output_metadata = model_metadata["outputs"][0]
    if "config" in model_config:  # gRPC ModelConfigResponse nests the config
        model_config = model_config["config"]
    max_batch_size = int(model_config.get("max_batch_size", 0))

    # gRPC-JSON renders int64 dims as strings
    shape = [int(s) for s in input_metadata["shape"]]
    if max_batch_size > 0:
        shape = shape[1:]  # strip dynamic batch dim
    if len(shape) != 3:
        raise Exception(f"expecting input rank 3, got {shape}")
    # CHW vs HWC: channels are 1 or 3
    if shape[0] in (1, 3):
        layout, (c, h, w) = "CHW", shape
    elif shape[2] in (1, 3):
        layout, (h, w, c) = "HWC", shape
    else:
        raise Exception(f"cannot infer layout from shape {shape}")
    return (
        input_metadata["name"],
        output_metadata["name"],
        c, h, w, layout,
        input_metadata["datatype"],
        max_batch_size,
    )


def preprocess(img, layout, dtype, c, h, w, scaling):
    """PIL image -> network-ready ndarray (reference :153-192)."""
    if c == 1:
        img = img.convert("L")
    else:
        img = img.convert("RGB")
    img = img.resize((w, h), 2)  # PIL.Image.BILINEAR
    arr = np.array(img).astype(np.float32)
    if c == 1:
        arr = arr[:, :, None]
    if scaling == "INCEPTION":
        arr = arr / 127.5 - 1.0
    elif scaling == "VGG":
        if c == 3:
            arr -= np.array([123.0, 117.0, 104.0], dtype=np.float32)
    if layout == "CHW":
        arr = np.transpose(arr, (2, 0, 1))
    from triton_client_tpu.utils import triton_to_np_dtype

    return arr.astype(triton_to_np_dtype(dtype))


def postprocess(results, output_name, batch_size, batching):
    """Print classification strings (reference :195-217); returns them."""
    output_array = results.as_numpy(output_name)
    out = []
    rows = output_array if batching else [output_array]
    for row in rows:
        for cls in np.asarray(row).reshape(-1):
            s = cls.decode("utf-8") if isinstance(cls, bytes) else str(cls)
            parts = s.split(":")
            if len(parts) >= 3:
                print(f"    {parts[0]} ({parts[1]}) = {parts[2]}")
            else:
                print(f"    {s}")
            out.append(s)
    return out


def requestGenerator(batched_data, input_name, output_name, dtype, args, protocol_mod):
    inp = protocol_mod.InferInput(input_name, list(batched_data.shape), dtype)
    inp.set_data_from_numpy(batched_data)
    output = protocol_mod.InferRequestedOutput(output_name, class_count=args.classes)
    yield [inp], [output]


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("image_filename", nargs="?", default=None)
    parser.add_argument("-m", "--model-name", default="simple_cnn")
    parser.add_argument("-x", "--model-version", default="")
    parser.add_argument("-b", "--batch-size", type=int, default=1)
    parser.add_argument("-c", "--classes", type=int, default=3)
    parser.add_argument("-s", "--scaling", default="NONE",
                        choices=["NONE", "INCEPTION", "VGG"])
    parser.add_argument("-u", "--url", default=None)
    parser.add_argument("-i", "--protocol", default="HTTP", choices=["HTTP", "GRPC"])
    parser.add_argument("-a", "--async", dest="async_set", action="store_true")
    parser.add_argument("--streaming", action="store_true")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    if args.streaming and args.protocol != "GRPC":
        print("streaming requires GRPC protocol")
        sys.exit(1)

    if args.protocol == "HTTP":
        import triton_client_tpu.http as protocol_mod

        url = args.url or "localhost:8000"
        client = protocol_mod.InferenceServerClient(
            url, verbose=args.verbose, concurrency=args.batch_size or 1)
        model_metadata = client.get_model_metadata(args.model_name, args.model_version)
        model_config = client.get_model_config(args.model_name, args.model_version)
    else:
        import triton_client_tpu.grpc as protocol_mod

        url = args.url or "localhost:8001"
        client = protocol_mod.InferenceServerClient(url, verbose=args.verbose)
        model_metadata = client.get_model_metadata(
            args.model_name, args.model_version, as_json=True)
        model_config = client.get_model_config(
            args.model_name, args.model_version, as_json=True)

    input_name, output_name, c, h, w, layout, dtype, max_batch = parse_model(
        model_metadata, model_config)

    if args.batch_size > max(max_batch, 1):
        print(f"batch size {args.batch_size} exceeds model max {max_batch}")
        sys.exit(1)

    from PIL import Image

    if args.image_filename:
        img = Image.open(args.image_filename)
    else:  # synthetic image so the example is self-contained
        rng = np.random.default_rng(0)
        img = Image.fromarray(
            rng.integers(0, 255, (h, w, 3), dtype=np.uint8), mode="RGB")

    image_data = preprocess(img, layout, dtype, c, h, w, args.scaling)
    batched = np.stack([image_data] * args.batch_size, axis=0) \
        if max_batch > 0 else image_data

    responses = []
    if args.streaming:
        completed: queue.Queue = queue.Queue()
        client.start_stream(partial(
            lambda q, result, error: q.put(error if error else result), completed))
        for inputs, outputs in requestGenerator(
                batched, input_name, output_name, dtype, args, protocol_mod):
            client.async_stream_infer(
                model_name=args.model_name, inputs=inputs, outputs=outputs)
        item = completed.get(timeout=60)
        client.stop_stream()
        if isinstance(item, Exception):
            print(f"streaming failed: {item}")
            sys.exit(1)
        responses.append(item)
    elif args.async_set:
        handles = []
        for inputs, outputs in requestGenerator(
                batched, input_name, output_name, dtype, args, protocol_mod):
            if args.protocol == "HTTP":
                handles.append(client.async_infer(
                    args.model_name, inputs, outputs=outputs))
            else:
                handles.append(client.async_infer(args.model_name, inputs,
                                                  outputs=outputs))
        responses = [hd.get_result() for hd in handles]
    else:
        for inputs, outputs in requestGenerator(
                batched, input_name, output_name, dtype, args, protocol_mod):
            responses.append(client.infer(
                args.model_name, inputs, outputs=outputs,
                model_version=args.model_version))

    ok = True
    for response in responses:
        classes = postprocess(response, output_name, args.batch_size, max_batch > 0)
        expect = args.classes * (args.batch_size if max_batch > 0 else 1)
        if len(classes) != expect:
            print(f"FAILED: expected {expect} classifications, got {len(classes)}")
            ok = False
    client.close()
    if not ok:
        sys.exit(1)
    print("PASS: image client")


if __name__ == "__main__":
    main()
