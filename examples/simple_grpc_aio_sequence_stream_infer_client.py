#!/usr/bin/env python3
"""asyncio bidi-stream sequences (reference
simple_grpc_aio_sequence_stream_infer_client.py)."""

import argparse
import asyncio
import sys

import numpy as np

import triton_client_tpu.grpc as grpcclient
from triton_client_tpu.grpc.aio import InferenceServerClient


async def run(url, verbose):
    values = [11, 7, 5, 3]
    async with InferenceServerClient(url, verbose=verbose) as client:
        async def requests():
            for i, v in enumerate(values):
                inp = grpcclient.InferInput("INPUT", [1], "INT32")
                inp.set_data_from_numpy(np.array([v], dtype=np.int32))
                yield {
                    "model_name": "simple_sequence",
                    "inputs": [inp],
                    "sequence_id": 4001,
                    "sequence_start": i == 0,
                    "sequence_end": i == len(values) - 1,
                }

        outs = []
        async for result, error in client.stream_infer(requests()):
            if error is not None:
                print(f"stream error: {error}")
                sys.exit(1)
            outs.append(int(result.as_numpy("OUTPUT")[0]))
        if outs != list(np.cumsum(values)):
            print(f"sequence mismatch: {outs}")
            sys.exit(1)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8001")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()
    asyncio.run(run(args.url, args.verbose))
    print("PASS: aio sequence stream")


if __name__ == "__main__":
    main()
