#!/usr/bin/env python3
"""Wire-conformance client built ONLY from freshly protoc-generated stubs.

Mirrors the reference's generated-stub examples
(/root/reference/src/grpc_generated/go/grpc_simple_client.go:66-201 and
 /root/reference/src/grpc_generated/javascript/client.js:42-69): a
third-party client that never touches the framework's own client library.
It proves the v2 wire protocol is language-neutral — anything protoc emits
from ``inference.proto`` interoperates with the server.

What this script deliberately does, to match the Go client's behavior spec:

* generates its OWN stubs at startup by invoking the stock ``protoc`` on
  ``triton_client_tpu/protocol/inference.proto`` into a temp dir (the
  reference instructs users to copy the protos and generate per-language
  stubs; see src/grpc_generated/go/README.md),
* imports nothing from ``triton_client_tpu``,
* calls the server through grpc *generic* channel methods with explicit
  ``/inference.GRPCInferenceService/<Method>`` paths (what every generated
  stub compiles down to),
* hand-packs tensor data as little-endian int32 bytes into
  ``raw_input_contents`` (grpc_simple_client.go: binary.Write little-endian)
  and hand-unpacks ``raw_output_contents`` (client.js BufferToInt32Array).

Exit code 0 + "PASS: wire conformance" on success.
"""

import argparse
import struct
import sys

import grpc

# stdlib-only shared protoc plumbing — keeps the "imports nothing from
# triton_client_tpu" constraint intact
from _raw_stub import SERVICE, generate_stubs, rpc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-u", "--url", default="localhost:8001")
    args = ap.parse_args()

    pb = generate_stubs()
    channel = grpc.insecure_channel(args.url)

    # -- health, as grpc_simple_client.go ServerLiveRequest/ServerReadyRequest
    live = rpc(channel, "ServerLive", pb.ServerLiveRequest(), pb.ServerLiveResponse)
    assert live.live, "server not live"
    ready = rpc(channel, "ServerReady", pb.ServerReadyRequest(), pb.ServerReadyResponse)
    assert ready.ready, "server not ready"

    # -- metadata, as grpc_simple_client.go ModelMetadataRequest
    meta = rpc(
        channel,
        "ModelMetadata",
        pb.ModelMetadataRequest(name="simple"),
        pb.ModelMetadataResponse,
    )
    assert meta.name == "simple", meta
    assert [t.name for t in meta.inputs] == ["INPUT0", "INPUT1"], meta
    assert [t.name for t in meta.outputs] == ["OUTPUT0", "OUTPUT1"], meta

    # -- infer with hand-packed little-endian int32 payloads
    #    (grpc_simple_client.go:120-160 packs via binary.Write LE)
    in0 = list(range(16))
    in1 = [1] * 16
    req = pb.ModelInferRequest(model_name="simple", id="conformance-1")
    for name in ("INPUT0", "INPUT1"):
        t = req.inputs.add()
        t.name = name
        t.datatype = "INT32"
        t.shape.extend([1, 16])
    for out_name in ("OUTPUT0", "OUTPUT1"):
        req.outputs.add().name = out_name
    req.raw_input_contents.append(struct.pack("<16i", *in0))
    req.raw_input_contents.append(struct.pack("<16i", *in1))

    resp = rpc(channel, "ModelInfer", req, pb.ModelInferResponse)
    assert resp.model_name == "simple", resp
    assert resp.id == "conformance-1", resp
    by_name = {o.name: i for i, o in enumerate(resp.outputs)}
    # client.js BufferToInt32Array-style unpack of raw_output_contents
    sums = struct.unpack("<16i", resp.raw_output_contents[by_name["OUTPUT0"]])
    diffs = struct.unpack("<16i", resp.raw_output_contents[by_name["OUTPUT1"]])
    for a, b, s, d in zip(in0, in1, sums, diffs):
        assert s == a + b, f"sum mismatch {a}+{b} != {s}"
        assert d == a - b, f"diff mismatch {a}-{b} != {d}"

    # -- bidi stream through the generic stream_stream method: two
    #    interleaved sequences (simple_grpc_sequence_stream semantics),
    #    still zero framework-client code.
    stream = channel.stream_stream(
        f"/{SERVICE}/ModelStreamInfer",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=pb.ModelStreamInferResponse.FromString,
    )

    def seq_req(seq_id, value, start, end):
        r = pb.ModelInferRequest(model_name="simple_sequence")
        r.parameters["sequence_id"].int64_param = seq_id
        r.parameters["sequence_start"].bool_param = start
        r.parameters["sequence_end"].bool_param = end
        t = r.inputs.add()
        t.name = "INPUT"
        t.datatype = "INT32"
        t.shape.extend([1])
        r.raw_input_contents.append(struct.pack("<i", value))
        return r

    values = [11, 7, 5, 3, 2, 0, 1]
    reqs = []
    for i, v in enumerate(values):
        start, end = i == 0, i == len(values) - 1
        reqs.append(seq_req(1001, v, start, end))
        reqs.append(seq_req(1002, -v, start, end))
    acc1 = acc2 = 0
    n_resp = 0
    for out in stream(iter(reqs), timeout=60):
        assert not out.error_message, out.error_message
        (got,) = struct.unpack("<i", out.infer_response.raw_output_contents[0])
        if got >= 0:
            acc1 = got
        else:
            acc2 = got
        n_resp += 1
    assert n_resp == len(reqs), f"expected {len(reqs)} responses, got {n_resp}"
    assert acc1 == sum(values), f"seq accumulator {acc1} != {sum(values)}"
    assert acc2 == -sum(values), f"seq accumulator {acc2} != {-sum(values)}"

    channel.close()

    print("PASS: wire conformance")
    return 0


if __name__ == "__main__":
    sys.exit(main())
