#!/usr/bin/env python3
"""Raw generated-stub client against ``simple`` (reference grpc_client.py:
health + metadata + ModelInfer on bare service_pb2 stubs, no client library).

Packs INT32 tensors into ``raw_input_contents`` little-endian and unpacks
``raw_output_contents`` positionally — the wire layout every generated stub
sees. Prints PASS on sum/diff verification.
"""

import argparse
import struct
import sys

import grpc

from _raw_stub import generate_stubs, rpc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-u", "--url", default="localhost:8001")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    pb = generate_stubs()
    channel = grpc.insecure_channel(args.url)

    live = rpc(channel, "ServerLive", pb.ServerLiveRequest(),
               pb.ServerLiveResponse)
    assert live.live, "server not live"
    ready = rpc(channel, "ServerReady", pb.ServerReadyRequest(),
                pb.ServerReadyResponse)
    assert ready.ready, "server not ready"
    meta = rpc(channel, "ModelMetadata", pb.ModelMetadataRequest(name="simple"),
               pb.ModelMetadataResponse)
    if args.verbose:
        print(meta)

    in0 = list(range(16))
    in1 = [1] * 16
    req = pb.ModelInferRequest(model_name="simple")
    for name, vals in (("INPUT0", in0), ("INPUT1", in1)):
        t = req.inputs.add()
        t.name = name
        t.datatype = "INT32"
        t.shape.extend([1, 16])
        req.raw_input_contents.append(struct.pack("<16i", *vals))
    for out_name in ("OUTPUT0", "OUTPUT1"):
        req.outputs.add().name = out_name

    resp = rpc(channel, "ModelInfer", req, pb.ModelInferResponse)
    outs = {}
    for i, out in enumerate(resp.outputs):
        outs[out.name] = struct.unpack("<16i", resp.raw_output_contents[i])

    for i in range(16):
        print(f"{in0[i]} + {in1[i]} = {outs['OUTPUT0'][i]}")
        print(f"{in0[i]} - {in1[i]} = {outs['OUTPUT1'][i]}")
        if outs["OUTPUT0"][i] != in0[i] + in1[i]:
            sys.exit("error: incorrect sum")
        if outs["OUTPUT1"][i] != in0[i] - in1[i]:
            sys.exit("error: incorrect difference")
    print("PASS: grpc_client")


if __name__ == "__main__":
    main()
