#!/usr/bin/env python3
"""Future-based async_infer over HTTP (reference
simple_http_async_infer_client.py behavior)."""

import argparse
import sys

import numpy as np

import triton_client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(args.url, concurrency=4,
                                              verbose=args.verbose)
    input0 = np.arange(16, dtype=np.int32).reshape(1, 16)
    input1 = np.ones((1, 16), dtype=np.int32)

    requests = []
    for _ in range(8):
        inputs = [
            httpclient.InferInput("INPUT0", [1, 16], "INT32"),
            httpclient.InferInput("INPUT1", [1, 16], "INT32"),
        ]
        inputs[0].set_data_from_numpy(input0)
        inputs[1].set_data_from_numpy(input1)
        requests.append(client.async_infer("simple", inputs))

    for req in requests:
        result = req.get_result()
        if not np.array_equal(result.as_numpy("OUTPUT0"), input0 + input1):
            print("sum mismatch")
            sys.exit(1)
    client.close()
    print("PASS: async infer")


if __name__ == "__main__":
    main()
