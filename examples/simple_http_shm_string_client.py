#!/usr/bin/env python3
"""Serialized BYTES tensors through system shm over HTTP against the
``simple_string`` sum/diff model (reference simple_http_shm_string_client.py:
both inputs AND both outputs live in shm regions :107-160; numeric strings are
length-prefix serialized into the input regions, results are deserialized out
of the output regions, and the example asserts no regions leak)."""

import argparse
import sys

import numpy as np

import triton_client_tpu.http as httpclient
import triton_client_tpu.utils.shared_memory as shm
from triton_client_tpu.utils import serialize_byte_tensor, serialized_byte_size


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(args.url, verbose=args.verbose)
    # start from a clean slate so stale registrations can't mask failures
    client.unregister_system_shared_memory()

    in0 = np.arange(16, dtype=np.int32)
    in1 = np.ones(16, dtype=np.int32)
    in0_str = np.array(
        [str(x).encode() for x in in0], dtype=object).reshape(1, 16)
    in1_str = np.array(
        [str(x).encode() for x in in1], dtype=object).reshape(1, 16)
    expect_sum = [str(x) for x in in0 + in1]
    expect_diff = [str(x) for x in in0 - in1]

    in0_ser = serialize_byte_tensor(in0_str)
    in1_ser = serialize_byte_tensor(in1_str)
    in0_size = serialized_byte_size(in0_str)
    in1_size = serialized_byte_size(in1_str)
    out_size = max(in0_size, in1_size) + 64  # room for sum/diff digits

    handles = {}
    try:
        for name, size in (("input0_data", in0_size), ("input1_data", in1_size),
                           ("output0_data", out_size), ("output1_data", out_size)):
            handles[name] = shm.create_shared_memory_region(
                name, f"/{name}", size)
            client.register_system_shared_memory(name, f"/{name}", size)
        shm.set_shared_memory_region(handles["input0_data"], [in0_ser])
        shm.set_shared_memory_region(handles["input1_data"], [in1_ser])

        inputs = []
        for name, region, size in (("INPUT0", "input0_data", in0_size),
                                   ("INPUT1", "input1_data", in1_size)):
            t = httpclient.InferInput(name, [1, 16], "BYTES")
            t.set_shared_memory(region, size)
            inputs.append(t)
        outputs = []
        for name, region in (("OUTPUT0", "output0_data"),
                             ("OUTPUT1", "output1_data")):
            o = httpclient.InferRequestedOutput(name)
            o.set_shared_memory(region, out_size)
            outputs.append(o)

        results = client.infer("simple_string", inputs, outputs=outputs)

        for oname, region, expect in (("OUTPUT0", "output0_data", expect_sum),
                                      ("OUTPUT1", "output1_data", expect_diff)):
            out = results.get_output(oname)
            if out is None:
                sys.exit(f"error: {oname} missing from response")
            got = shm.get_contents_as_numpy(
                handles[region], np.object_, [1, 16])
            got_strs = [bytes(x).decode() for x in got.reshape(-1)]
            for i, (g, e) in enumerate(zip(got_strs, expect)):
                if g != e:
                    sys.exit(f"error: {oname}[{i}] = {g}, expected {e}")

        # leak check: exactly our four regions registered, then zero
        status = client.get_system_shared_memory_status()
        if len(status) != 4:
            sys.exit(f"error: expected 4 registered regions, got {status}")
        client.unregister_system_shared_memory()
        status = client.get_system_shared_memory_status()
        if len(status) != 0:
            sys.exit(f"error: regions leaked after unregister: {status}")
    finally:
        for h in handles.values():
            shm.destroy_shared_memory_region(h)
        client.close()
    print("PASS: system shared memory string")


if __name__ == "__main__":
    main()
