#!/usr/bin/env python3
"""Reuse InferInput/InferRequestedOutput objects across calls (reference
reuse_infer_objects_client.py behavior)."""

import argparse
import sys

import numpy as np

import triton_client_tpu.http as httpclient


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("-u", "--url", default="localhost:8000")
    parser.add_argument("-v", "--verbose", action="store_true")
    args = parser.parse_args()

    client = httpclient.InferenceServerClient(args.url, verbose=args.verbose)
    inputs = [
        httpclient.InferInput("INPUT0", [1, 16], "INT32"),
        httpclient.InferInput("INPUT1", [1, 16], "INT32"),
    ]
    outputs = [
        httpclient.InferRequestedOutput("OUTPUT0"),
        httpclient.InferRequestedOutput("OUTPUT1"),
    ]
    # wire fast path: reused InferInput objects + a prepared template —
    # prepare() compiles the request skeleton once, each round re-stamps
    # only the tensor bytes (and the auto-generated request id)
    prep = None
    for round_num in range(3):
        input0 = np.full((1, 16), round_num, dtype=np.int32)
        input1 = np.arange(16, dtype=np.int32).reshape(1, 16)
        inputs[0].set_data_from_numpy(input0)
        inputs[1].set_data_from_numpy(input1)
        if prep is None:
            prep = client.prepare("simple", inputs, outputs=outputs)
        result = prep.infer()
        if not np.array_equal(result.as_numpy("OUTPUT0"), input0 + input1):
            print(f"sum mismatch in round {round_num}")
            sys.exit(1)
        if not np.array_equal(result.as_numpy("OUTPUT1"), input0 - input1):
            print(f"diff mismatch in round {round_num}")
            sys.exit(1)
    client.close()
    print("PASS: reuse infer objects")


if __name__ == "__main__":
    main()
