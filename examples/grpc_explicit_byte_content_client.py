#!/usr/bin/env python3
"""Explicit typed-content infer with BYTES: each string element travels as
its own entry in ``contents.bytes_contents`` — no length-prefixed
serialization on the request (reference
grpc_explicit_byte_content_client.py:77-87) — against the ``simple_string``
sum/diff-over-decimal-strings model. The raw response IS length-prefixed, so
outputs go through the client library's BYTES deserializer.
"""

import argparse
import sys

import grpc

from _raw_stub import generate_stubs, rpc
from triton_client_tpu.utils import deserialize_bytes_tensor


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-u", "--url", default="localhost:8001")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    pb = generate_stubs()
    channel = grpc.insecure_channel(args.url)

    in0 = list(range(16))
    in1 = [1] * 16
    req = pb.ModelInferRequest(model_name="simple_string")
    for name, vals in (("INPUT0", in0), ("INPUT1", in1)):
        t = req.inputs.add()
        t.name = name
        t.datatype = "BYTES"
        t.shape.extend([1, 16])
        for v in vals:
            t.contents.bytes_contents.append(str(v).encode("utf-8"))
    for out_name in ("OUTPUT0", "OUTPUT1"):
        req.outputs.add().name = out_name

    resp = rpc(channel, "ModelInfer", req, pb.ModelInferResponse)
    outs = {}
    for i, out in enumerate(resp.outputs):
        assert out.datatype == "BYTES", out
        outs[out.name] = deserialize_bytes_tensor(
            resp.raw_output_contents[i]).reshape(-1)

    for i in range(16):
        got_sum = int(outs["OUTPUT0"][i].decode())
        got_diff = int(outs["OUTPUT1"][i].decode())
        print(f"{in0[i]} + {in1[i]} = {got_sum}")
        print(f"{in0[i]} - {in1[i]} = {got_diff}")
        if got_sum != in0[i] + in1[i]:
            sys.exit("error: incorrect sum")
        if got_diff != in0[i] - in1[i]:
            sys.exit("error: incorrect difference")
    print("PASS: explicit byte content")


if __name__ == "__main__":
    main()
