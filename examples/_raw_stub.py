"""Shared plumbing for the raw generated-stub examples.

The reference's ``grpc_client.py`` / ``grpc_explicit_*_content_client.py`` /
``grpc_image_client.py`` import pre-generated ``service_pb2`` stubs from the
client wheel. This framework's equivalents generate their stubs at startup by
invoking the stock ``protoc`` on ``triton_client_tpu/protocol/inference.proto``
— the same flow a third-party user follows (reference
src/grpc_generated/go/README.md) — and call the server through grpc *generic*
channel methods, which is what every generated stub compiles down to.
"""

import importlib.util
import os
import shutil
import subprocess
import sys
import tempfile

SERVICE = "inference.GRPCInferenceService"


def generate_stubs():
    """protoc-compile the framework IDL and import the resulting module."""
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    proto_dir = os.path.join(repo_root, "triton_client_tpu", "protocol")
    protoc = shutil.which("protoc")
    if protoc is None:
        print("SKIP: protoc not found", file=sys.stderr)
        sys.exit(2)
    with tempfile.TemporaryDirectory(prefix="raw_stub_") as tmp:
        subprocess.run(
            [protoc, f"--proto_path={proto_dir}", f"--python_out={tmp}",
             "inference.proto"],
            check=True,
        )
        spec = importlib.util.spec_from_file_location(
            "raw_stub_inference_pb2", os.path.join(tmp, "inference_pb2.py"))
        mod = importlib.util.module_from_spec(spec)
        # exec fully materializes the descriptors; the source dir can go
        spec.loader.exec_module(mod)
    return mod


def rpc(channel, method, pb_req, resp_cls, timeout=30):
    call = channel.unary_unary(
        f"/{SERVICE}/{method}",
        request_serializer=lambda m: m.SerializeToString(),
        response_deserializer=resp_cls.FromString,
    )
    return call(pb_req, timeout=timeout)
