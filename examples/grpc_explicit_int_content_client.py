#!/usr/bin/env python3
"""Explicit typed-content infer: INT32 values travel in the request's
``contents.int_contents`` repeated field instead of ``raw_input_contents``
(reference grpc_explicit_int_content_client.py:75-95). The server replies
raw; outputs are unpacked positionally from ``raw_output_contents``.
"""

import argparse
import sys

import grpc
import numpy as np

from _raw_stub import generate_stubs, rpc


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-u", "--url", default="localhost:8001")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args()

    pb = generate_stubs()
    channel = grpc.insecure_channel(args.url)

    in0 = [i for i in range(16)]
    in1 = [1 for _ in range(16)]
    req = pb.ModelInferRequest(model_name="simple")
    for name, vals in (("INPUT0", in0), ("INPUT1", in1)):
        t = req.inputs.add()
        t.name = name
        t.datatype = "INT32"
        t.shape.extend([1, 16])
        t.contents.int_contents[:] = vals
    for out_name in ("OUTPUT0", "OUTPUT1"):
        req.outputs.add().name = out_name

    resp = rpc(channel, "ModelInfer", req, pb.ModelInferResponse)
    outs = {}
    for i, out in enumerate(resp.outputs):
        arr = np.frombuffer(resp.raw_output_contents[i], dtype=np.int32)
        # reshape (not np.resize): a wrong-size payload must fail loudly
        outs[out.name] = arr.reshape([int(d) for d in out.shape]).reshape(-1)

    for i in range(16):
        print(f"{in0[i]} + {in1[i]} = {outs['OUTPUT0'][i]}")
        print(f"{in0[i]} - {in1[i]} = {outs['OUTPUT1'][i]}")
        if outs["OUTPUT0"][i] != in0[i] + in1[i]:
            sys.exit("error: incorrect sum")
        if outs["OUTPUT1"][i] != in0[i] - in1[i]:
            sys.exit("error: incorrect difference")
    print("PASS: explicit int content")


if __name__ == "__main__":
    main()
