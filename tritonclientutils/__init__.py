"""Deprecated flat-layout alias (reference parity: tritonclientutils/
re-exports the packaged layout with a DeprecationWarning)."""

import warnings

warnings.warn(
    "tritonclientutils is deprecated; use tritonclient.utils or "
    "triton_client_tpu.utils",
    DeprecationWarning,
    stacklevel=2,
)

from triton_client_tpu.utils import *  # noqa: E402,F401,F403
