"""Drop-in compatibility namespace: ``tritonclient`` → ``triton_client_tpu``.

Reference users write ``import tritonclient.http as httpclient`` /
``import tritonclient.grpc`` / ``from tritonclient.utils import *``
(reference src/python/examples/simple_http_infer_client.py and the whole
example corpus).  This package lets that code run unchanged against the
TPU-native framework: a meta-path finder redirects every
``tritonclient.<sub>`` import to the corresponding
``triton_client_tpu.<sub>`` module, lazily, so optional transport deps
(aiohttp, grpcio) are only pulled in when the matching subpackage is
imported — same behavior as the real layout.

This is the analog of the reference's own alias-package pattern
(tritonhttpclient/tritongrpcclient/... re-export the new layout with a
DeprecationWarning); here the alias is not deprecated — it is the
compatibility surface.
"""

import importlib
import importlib.abc
import importlib.machinery
import importlib.util
import sys

_TARGET = "triton_client_tpu"


class _AliasLoader(importlib.abc.Loader):
    def __init__(self, alias, target):
        self._alias = alias
        self._target = target
        self._real_attrs = None

    def create_module(self, spec):
        mod = importlib.import_module(self._target)
        # The import machinery will stamp the alias spec onto the module we
        # return; remember the canonical attributes so exec_module can
        # restore them (the module must keep identifying as its real name).
        self._real_attrs = {
            k: getattr(mod, k, None)
            for k in ("__spec__", "__loader__", "__package__", "__name__")
        }
        # Register under the alias name too, so submodule imports and
        # pickling see one canonical module object.
        sys.modules.setdefault(self._alias, mod)
        return mod

    def exec_module(self, module):
        # Already executed under its real name — just undo the alias-spec
        # stamping done by _init_module_attrs.
        for k, v in (self._real_attrs or {}).items():
            if v is not None:
                setattr(module, k, v)


class _AliasFinder(importlib.abc.MetaPathFinder):
    def find_spec(self, fullname, path=None, target=None):
        if fullname == "tritonclient" or not fullname.startswith("tritonclient."):
            return None
        real = _TARGET + fullname[len("tritonclient"):]
        try:
            real_spec = importlib.util.find_spec(real)
        except ModuleNotFoundError:
            return None
        if real_spec is None:
            return None
        spec = importlib.machinery.ModuleSpec(
            fullname, _AliasLoader(fullname, real), is_package=real_spec.submodule_search_locations is not None
        )
        return spec


if not any(isinstance(f, _AliasFinder) for f in sys.meta_path):
    sys.meta_path.append(_AliasFinder())

# Top-level conveniences the reference exposes on `tritonclient` itself.
from triton_client_tpu import __version__  # noqa: E402,F401
