// C ABI for POSIX system shared memory used by the Python wheel.
//
// Parity target: reference
// src/python/library/tritonclient/utils/shared_memory/shared_memory.h:39-47
// (SharedMemoryRegionCreate/Set/GetInfo/Destroy with negative error codes).
// Re-designed (not translated): same contract, plus SharedMemoryRegionOpen for
// attaching to a region created by another process (needed by the TPU serving
// harness for cross-process zero-wire-copy staging).

#pragma once

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

// Error codes (match the reference's -1..-6 convention,
// utils/shared_memory/__init__.py:314-340).
typedef enum {
  CSHM_SUCCESS = 0,
  CSHM_ERROR_UNKNOWN = -1,
  CSHM_ERROR_SHM_OPEN = -2,
  CSHM_ERROR_SHM_TRUNCATE = -3,
  CSHM_ERROR_SHM_MMAP = -4,
  CSHM_ERROR_SHM_UNMAP = -5,
  CSHM_ERROR_SHM_UNLINK = -6,
  CSHM_ERROR_INVALID_HANDLE = -7,
  CSHM_ERROR_OUT_OF_BOUNDS = -8,
} CshmError;

// Opaque region handle.
typedef void* CshmHandle;

// Create (shm_open O_CREAT + ftruncate + mmap) a shared memory region named
// `shm_key` of `byte_size` bytes, mapped read/write.  `triton_shm_name` is the
// logical name used on the wire for register/unregister RPCs.  When
// `exclusive` is nonzero the call fails if the object already exists
// (O_EXCL) instead of silently attaching to and resizing it.
int SharedMemoryRegionCreate(const char* triton_shm_name, const char* shm_key,
                             size_t byte_size, int exclusive,
                             CshmHandle* handle);

// Attach to an existing region (no O_CREAT, no ftruncate).
int SharedMemoryRegionOpen(const char* triton_shm_name, const char* shm_key,
                           size_t byte_size, size_t offset, CshmHandle* handle);

// Copy `byte_size` bytes from `data` into the region at `offset`.
int SharedMemoryRegionSet(CshmHandle handle, size_t offset, size_t byte_size,
                          const void* data);

// Introspection: fetch the fields of a handle.
int GetSharedMemoryHandleInfo(CshmHandle handle, char** base_addr,
                              const char** shm_key, int* shm_fd, size_t* offset,
                              size_t* byte_size);

// Unmap; when `unlink` != 0 also shm_unlink the backing object (creator side).
int SharedMemoryRegionDestroy(CshmHandle handle, int unlink);

#ifdef __cplusplus
}
#endif
