// POSIX shared-memory C shim backing triton_client_tpu.utils.shared_memory.
//
// Behavioral parity with the reference shim
// (src/python/library/tritonclient/utils/shared_memory/shared_memory.cc):
// shm_open/ftruncate/mmap on create, memcpy on set, munmap/shm_unlink on
// destroy, with a handle struct carrying {name, base_addr, shm_key, shm_fd,
// offset, byte_size}.  Written fresh for this framework; adds an open-existing
// path and bounds checking on Set.

#include "shared_memory.h"

#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdlib>
#include <new>
#include <string>

namespace {

struct SharedMemoryHandle {
  std::string triton_shm_name;
  std::string shm_key;
  char* base_addr = nullptr;  // == map_addr + (offset - aligned file offset)
  char* map_addr = nullptr;   // actual mmap return, for munmap
  size_t map_size = 0;
  int shm_fd = -1;
  size_t offset = 0;
  size_t byte_size = 0;
};

// mmap requires a page-aligned file offset; map from the aligned floor and
// return the interior pointer at the requested offset.
int MapRegion(int shm_fd, size_t offset, size_t byte_size, char** addr,
              char** map_addr, size_t* map_size) {
  const size_t page = static_cast<size_t>(sysconf(_SC_PAGESIZE));
  const size_t aligned = offset - (offset % page);
  const size_t delta = offset - aligned;
  void* p = mmap(nullptr, byte_size + delta, PROT_READ | PROT_WRITE, MAP_SHARED,
                 shm_fd, static_cast<off_t>(aligned));
  if (p == MAP_FAILED) {
    return CSHM_ERROR_SHM_MMAP;
  }
  *map_addr = static_cast<char*>(p);
  *map_size = byte_size + delta;
  *addr = *map_addr + delta;
  return CSHM_SUCCESS;
}

}  // namespace

extern "C" {

int SharedMemoryRegionCreate(const char* triton_shm_name, const char* shm_key,
                             size_t byte_size, int exclusive,
                             CshmHandle* handle) {
  int flags = O_RDWR | O_CREAT;
  if (exclusive != 0) {
    flags |= O_EXCL;  // "create only": fail if the object already exists
  }
  int fd = shm_open(shm_key, flags, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return CSHM_ERROR_SHM_OPEN;
  }
  if (ftruncate(fd, static_cast<off_t>(byte_size)) == -1) {
    close(fd);
    shm_unlink(shm_key);  // don't leak the object we just created
    return CSHM_ERROR_SHM_TRUNCATE;
  }
  char* addr = nullptr;
  char* map_addr = nullptr;
  size_t map_size = 0;
  int err = MapRegion(fd, 0, byte_size, &addr, &map_addr, &map_size);
  if (err != CSHM_SUCCESS) {
    close(fd);
    shm_unlink(shm_key);
    return err;
  }
  auto* h = new (std::nothrow) SharedMemoryHandle();
  if (h == nullptr) {
    munmap(map_addr, map_size);
    close(fd);
    shm_unlink(shm_key);
    return CSHM_ERROR_UNKNOWN;
  }
  h->triton_shm_name = triton_shm_name;
  h->shm_key = shm_key;
  h->base_addr = addr;
  h->map_addr = map_addr;
  h->map_size = map_size;
  h->shm_fd = fd;
  h->offset = 0;
  h->byte_size = byte_size;
  *handle = h;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionOpen(const char* triton_shm_name, const char* shm_key,
                           size_t byte_size, size_t offset, CshmHandle* handle) {
  int fd = shm_open(shm_key, O_RDWR, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return CSHM_ERROR_SHM_OPEN;
  }
  char* addr = nullptr;
  char* map_addr = nullptr;
  size_t map_size = 0;
  int err = MapRegion(fd, offset, byte_size, &addr, &map_addr, &map_size);
  if (err != CSHM_SUCCESS) {
    close(fd);
    return err;
  }
  auto* h = new (std::nothrow) SharedMemoryHandle();
  if (h == nullptr) {
    munmap(map_addr, map_size);
    close(fd);
    return CSHM_ERROR_UNKNOWN;
  }
  h->triton_shm_name = triton_shm_name;
  h->shm_key = shm_key;
  h->base_addr = addr;
  h->map_addr = map_addr;
  h->map_size = map_size;
  h->shm_fd = fd;
  h->offset = offset;
  h->byte_size = byte_size;
  *handle = h;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionSet(CshmHandle handle, size_t offset, size_t byte_size,
                          const void* data) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr || h->base_addr == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  // Overflow-safe bounds check (offset + byte_size could wrap in size_t).
  if (offset > h->byte_size || byte_size > h->byte_size - offset) {
    return CSHM_ERROR_OUT_OF_BOUNDS;
  }
  memcpy(h->base_addr + offset, data, byte_size);
  return CSHM_SUCCESS;
}

int GetSharedMemoryHandleInfo(CshmHandle handle, char** base_addr,
                              const char** shm_key, int* shm_fd, size_t* offset,
                              size_t* byte_size) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  *base_addr = h->base_addr;
  *shm_key = h->shm_key.c_str();
  *shm_fd = h->shm_fd;
  *offset = h->offset;
  *byte_size = h->byte_size;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionDestroy(CshmHandle handle, int unlink) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  int rc = CSHM_SUCCESS;
  if (h->map_addr != nullptr && munmap(h->map_addr, h->map_size) == -1) {
    rc = CSHM_ERROR_SHM_UNMAP;
  }
  if (h->shm_fd != -1) {
    close(h->shm_fd);
  }
  if (rc == CSHM_SUCCESS && unlink != 0 &&
      shm_unlink(h->shm_key.c_str()) == -1) {
    rc = CSHM_ERROR_SHM_UNLINK;
  }
  delete h;
  return rc;
}

}  // extern "C"
