// POSIX shared-memory C shim backing triton_client_tpu.utils.shared_memory.
//
// Behavioral parity with the reference shim
// (src/python/library/tritonclient/utils/shared_memory/shared_memory.cc):
// shm_open/ftruncate/mmap on create, memcpy on set, munmap/shm_unlink on
// destroy, with a handle struct carrying {name, base_addr, shm_key, shm_fd,
// offset, byte_size}.  Written fresh for this framework; adds an open-existing
// path and bounds checking on Set.

#include "shared_memory.h"

#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdlib>
#include <new>
#include <string>

namespace {

struct SharedMemoryHandle {
  std::string triton_shm_name;
  std::string shm_key;
  char* base_addr = nullptr;
  int shm_fd = -1;
  size_t offset = 0;
  size_t byte_size = 0;
};

int MapRegion(int shm_fd, size_t offset, size_t byte_size, char** addr) {
  void* p = mmap(nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, shm_fd,
                 static_cast<off_t>(offset));
  if (p == MAP_FAILED) {
    return CSHM_ERROR_SHM_MMAP;
  }
  *addr = static_cast<char*>(p);
  return CSHM_SUCCESS;
}

}  // namespace

extern "C" {

int SharedMemoryRegionCreate(const char* triton_shm_name, const char* shm_key,
                             size_t byte_size, CshmHandle* handle) {
  int fd = shm_open(shm_key, O_RDWR | O_CREAT, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return CSHM_ERROR_SHM_OPEN;
  }
  if (ftruncate(fd, static_cast<off_t>(byte_size)) == -1) {
    close(fd);
    shm_unlink(shm_key);  // don't leak the object we just created
    return CSHM_ERROR_SHM_TRUNCATE;
  }
  char* addr = nullptr;
  int err = MapRegion(fd, 0, byte_size, &addr);
  if (err != CSHM_SUCCESS) {
    close(fd);
    shm_unlink(shm_key);
    return err;
  }
  auto* h = new (std::nothrow) SharedMemoryHandle();
  if (h == nullptr) {
    munmap(addr, byte_size);
    close(fd);
    shm_unlink(shm_key);
    return CSHM_ERROR_UNKNOWN;
  }
  h->triton_shm_name = triton_shm_name;
  h->shm_key = shm_key;
  h->base_addr = addr;
  h->shm_fd = fd;
  h->offset = 0;
  h->byte_size = byte_size;
  *handle = h;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionOpen(const char* triton_shm_name, const char* shm_key,
                           size_t byte_size, size_t offset, CshmHandle* handle) {
  int fd = shm_open(shm_key, O_RDWR, S_IRUSR | S_IWUSR);
  if (fd == -1) {
    return CSHM_ERROR_SHM_OPEN;
  }
  char* addr = nullptr;
  int err = MapRegion(fd, offset, byte_size, &addr);
  if (err != CSHM_SUCCESS) {
    close(fd);
    return err;
  }
  auto* h = new (std::nothrow) SharedMemoryHandle();
  if (h == nullptr) {
    munmap(addr, byte_size);
    close(fd);
    return CSHM_ERROR_UNKNOWN;
  }
  h->triton_shm_name = triton_shm_name;
  h->shm_key = shm_key;
  h->base_addr = addr;
  h->shm_fd = fd;
  h->offset = offset;
  h->byte_size = byte_size;
  *handle = h;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionSet(CshmHandle handle, size_t offset, size_t byte_size,
                          const void* data) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr || h->base_addr == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  if (offset + byte_size > h->byte_size) {
    return CSHM_ERROR_OUT_OF_BOUNDS;
  }
  memcpy(h->base_addr + offset, data, byte_size);
  return CSHM_SUCCESS;
}

int GetSharedMemoryHandleInfo(CshmHandle handle, char** base_addr,
                              const char** shm_key, int* shm_fd, size_t* offset,
                              size_t* byte_size) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  *base_addr = h->base_addr;
  *shm_key = h->shm_key.c_str();
  *shm_fd = h->shm_fd;
  *offset = h->offset;
  *byte_size = h->byte_size;
  return CSHM_SUCCESS;
}

int SharedMemoryRegionDestroy(CshmHandle handle, int unlink) {
  auto* h = static_cast<SharedMemoryHandle*>(handle);
  if (h == nullptr) {
    return CSHM_ERROR_INVALID_HANDLE;
  }
  int rc = CSHM_SUCCESS;
  if (h->base_addr != nullptr && munmap(h->base_addr, h->byte_size) == -1) {
    rc = CSHM_ERROR_SHM_UNMAP;
  }
  if (h->shm_fd != -1) {
    close(h->shm_fd);
  }
  if (rc == CSHM_SUCCESS && unlink != 0 &&
      shm_unlink(h->shm_key.c_str()) == -1) {
    rc = CSHM_ERROR_SHM_UNLINK;
  }
  delete h;
  return rc;
}

}  // extern "C"
