// Shared HTTP/1.1 socket transport used by both C++ clients (the HTTP
// client directly; the gRPC client for gRPC-Web framed requests).
// Dependency-free replacement for the reference's libcurl/grpc++ transports.
#pragma once

#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"
#include "tls.h"

namespace tc_tpu {
namespace client {

using Headers = std::map<std::string, std::string>;

class HttpTransport {
 public:
  struct Response {
    int status = 0;
    Headers headers;  // lower-cased keys
    std::string body;
  };

  HttpTransport(std::string host, int port, size_t max_idle_conns);
  ~HttpTransport();

  // Enable TCP-level keepalive probes on every unary connection this
  // transport opens (streaming DuplexConnections read the settings via the
  // accessors below and apply them at Open). This is the socket-transport
  // translation of gRPC's HTTP/2 keepalive pings (reference
  // KeepAliveOptions, grpc_client.h:62-86): idle_s before the first probe,
  // intvl_s between probes.
  void SetTcpKeepAlive(int idle_s, int intvl_s);
  int keepalive_idle_s() const { return keepalive_idle_s_; }
  int keepalive_intvl_s() const { return keepalive_intvl_s_; }

  // Cap the accepted response body size in bytes (reference
  // GRPC_ARG_MAX_RECEIVE_MESSAGE_LENGTH); 0 = unlimited.
  void SetMaxResponseBytes(size_t max_bytes);
  size_t max_response_bytes() const { return max_response_bytes_; }

  // Cap the request body size in bytes (reference
  // GRPC_ARG_MAX_SEND_MESSAGE_LENGTH); 0 = unlimited.
  void SetMaxRequestBytes(size_t max_bytes);
  size_t max_request_bytes() const { return max_request_bytes_; }

  // Speak TLS on every connection (reference HttpSslOptions / libcurl
  // CURLOPT_SSL_*; backed by the system libssl via tls.{h,cc}).  Builds
  // the shared SSL_CTX once — bad CA/cert/key paths fail HERE, not on the
  // first request.
  Error EnableTls(const HttpSslOptionsView& opts);
  bool tls_enabled() const { return use_tls_; }
  const TlsContext* tls_context() const {
    return use_tls_ ? &tls_ctx_ : nullptr;
  }

  HttpTransport(const HttpTransport&) = delete;
  HttpTransport& operator=(const HttpTransport&) = delete;

  const std::string& host() const { return host_; }
  int port() const { return port_; }

  // timeout_us > 0 applies a client-side deadline to the socket I/O for
  // this request (reference CURLOPT_TIMEOUT_MS, http_client.cc:2163-2166);
  // an expired deadline returns an Error mentioning "Deadline Exceeded".
  Error Request(
      const std::string& method, const std::string& path,
      const std::string& body, const Headers& extra_headers, Response* out,
      RequestTimers* timers = nullptr, uint64_t timeout_us = 0);

 private:
  // one pooled connection: the socket plus its TLS session (null = plain)
  struct Conn {
    int fd = -1;
    TlsSession* tls = nullptr;
  };
  void Release(Conn conn, bool reusable);

  std::string host_;
  int port_;
  size_t max_idle_;
  int keepalive_idle_s_ = 0;   // 0 = TCP keepalive disabled
  int keepalive_intvl_s_ = 0;
  size_t max_response_bytes_ = 0;
  size_t max_request_bytes_ = 0;
  bool use_tls_ = false;
  TlsContext tls_ctx_;
  std::mutex mu_;
  std::vector<Conn> idle_;
};

std::string Base64Encode(const uint8_t* data, size_t len);

// One full-duplex HTTP/1.1 exchange on a dedicated connection: the request
// body is sent incrementally as chunked transfer coding while the response
// (headers + chunked body) is read concurrently.  This is what makes live
// gRPC-Web streaming possible without grpc++ — the reference achieves the
// same duplexing with a grpc::ClientReaderWriter
// (/root/reference/src/c++/library/grpc_client.cc:1377-1673).
class DuplexConnection {
 public:
  DuplexConnection() = default;
  ~DuplexConnection();

  DuplexConnection(const DuplexConnection&) = delete;
  DuplexConnection& operator=(const DuplexConnection&) = delete;

  // Connects and sends the request headers (Transfer-Encoding: chunked).
  // keepalive_idle_s > 0 enables TCP keepalive probes on the (long-lived)
  // stream socket — the connection keepalive matters most for.
  // tls_ctx non-null wraps the stream in TLS before the HTTP exchange.
  Error Open(
      const std::string& host, int port, const std::string& path,
      const Headers& extra_headers, int keepalive_idle_s = 0,
      int keepalive_intvl_s = 0, const TlsContext* tls_ctx = nullptr);
  // Sends one chunk of request body (thread-safe w.r.t. reads, not writes).
  Error WriteChunk(const std::string& data);
  // Sends the terminal zero chunk: request body complete.
  Error WriteEnd();

  // Blocks until the response status line + headers arrive.
  Error ReadResponseHeaders(int* status, Headers* headers);
  // Appends the next available decoded body bytes to `out`; sets *done when
  // the body is complete.  Blocks until data, end, or error.
  Error ReadSome(std::string* out, bool* done);

  void Close();

 private:
  int fd_ = -1;
  TlsSession* tls_ = nullptr;
  // response framing state
  bool headers_read_ = false;
  bool chunked_ = false;
  long long remaining_ = -1;  // bytes left in current chunk / content-length
  bool body_done_ = false;
  std::string rbuf_;  // raw bytes received, not yet decoded
  // recv more into rbuf_.  With `eof` null, a peer close is an error; with
  // `eof` non-null it is reported there (close-delimited bodies).
  Error Fill(bool* eof = nullptr);
};

}  // namespace client
}  // namespace tc_tpu
