// Shared HTTP/1.1 socket transport used by both C++ clients (the HTTP
// client directly; the gRPC client for gRPC-Web framed requests).
// Dependency-free replacement for the reference's libcurl/grpc++ transports.
#pragma once

#include <map>
#include <mutex>
#include <string>
#include <vector>

#include "common.h"

namespace tc_tpu {
namespace client {

using Headers = std::map<std::string, std::string>;

class HttpTransport {
 public:
  struct Response {
    int status = 0;
    Headers headers;  // lower-cased keys
    std::string body;
  };

  HttpTransport(std::string host, int port, size_t max_idle_conns);
  ~HttpTransport();

  HttpTransport(const HttpTransport&) = delete;
  HttpTransport& operator=(const HttpTransport&) = delete;

  const std::string& host() const { return host_; }
  int port() const { return port_; }

  Error Request(
      const std::string& method, const std::string& path,
      const std::string& body, const Headers& extra_headers, Response* out,
      RequestTimers* timers = nullptr);

 private:
  int Connect(Error* err);
  void Release(int fd, bool reusable);

  std::string host_;
  int port_;
  size_t max_idle_;
  std::mutex mu_;
  std::vector<int> idle_;
};

std::string Base64Encode(const uint8_t* data, size_t len);

}  // namespace client
}  // namespace tc_tpu
