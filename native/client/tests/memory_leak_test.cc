// Memory-growth test (reference model: src/c++/tests/memory_leak_test.cc —
// loop sync/async infers and fail on unbounded growth).  RSS is sampled from
// /proc/self/status after a warm-up phase so allocator steady-state, pools,
// and lazily-started worker threads do not count as leaks.
//
// Usage: memory_leak_test <http_host:port> [iterations]

#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(expr)                                                \
  do {                                                                \
    tc::Error err__ = (expr);                                         \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,       \
              err__.Message().c_str());                               \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

namespace {

long RssKb() {
  FILE* f = fopen("/proc/self/status", "r");
  if (!f) return -1;
  char line[256];
  long kb = -1;
  while (fgets(line, sizeof(line), f)) {
    if (strncmp(line, "VmRSS:", 6) == 0) {
      kb = atol(line + 6);
      break;
    }
  }
  fclose(f);
  return kb;
}

template <typename ClientT>
void RunIterations(ClientT* client, int n) {
  for (int it = 0; it < n; ++it) {
    std::vector<int32_t> input0(16), input1(16);
    for (int i = 0; i < 16; ++i) {
      input0[i] = i + it;
      input1[i] = 2;
    }
    tc::InferInput *in0, *in1;
    CHECK_OK(tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32"));
    CHECK_OK(tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32"));
    CHECK_OK(in0->AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        input0.size() * sizeof(int32_t)));
    CHECK_OK(in1->AppendRaw(
        reinterpret_cast<const uint8_t*>(input1.data()),
        input1.size() * sizeof(int32_t)));
    tc::InferOptions options("simple");
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {in0, in1}));
    const uint8_t* buf;
    size_t len;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
    if (*reinterpret_cast<const int32_t*>(buf) != input0[0] + 2) {
      fprintf(stderr, "FAILED: wrong result at iteration %d\n", it);
      exit(1);
    }
    delete result;
    delete in0;
    delete in1;
  }
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port> [iterations]\n", argv[0]);
    return 2;
  }
  const std::string url = argv[1];
  const int iterations = argc > 2 ? atoi(argv[2]) : 500;

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&http_client, url));
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&grpc_client, url));

  // warm up: connection pools, lazily-spawned worker threads, allocator
  RunIterations(http_client.get(), 50);
  RunIterations(grpc_client.get(), 50);

  long before_kb = RssKb();
  RunIterations(http_client.get(), iterations);
  RunIterations(grpc_client.get(), iterations);
  long after_kb = RssKb();

  long growth_kb = after_kb - before_kb;
  printf("rss before=%ldkB after=%ldkB growth=%ldkB over %d iterations\n",
         before_kb, after_kb, growth_kb, 2 * iterations);
  // steady-state request loops must not accumulate memory; allow modest
  // allocator noise
  if (growth_kb > 8 * 1024) {
    fprintf(stderr, "FAILED: rss grew %ldkB (> 8MB)\n", growth_kb);
    return 1;
  }
  printf("PASS: memory leak test\n");
  return 0;
}
