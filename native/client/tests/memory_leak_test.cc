// Memory-growth test (reference model: src/c++/tests/memory_leak_test.cc —
// loop sync/async infers and fail on unbounded growth).  RSS is sampled from
// /proc/self/status after a warm-up phase so allocator steady-state, pools,
// and lazily-started worker threads do not count as leaks.
//
// Usage: memory_leak_test <http_host:port> [iterations]

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(expr)                                                \
  do {                                                                \
    tc::Error err__ = (expr);                                         \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,       \
              err__.Message().c_str());                               \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

namespace {

long RssKb() {
  FILE* f = fopen("/proc/self/status", "r");
  if (!f) return -1;
  char line[256];
  long kb = -1;
  while (fgets(line, sizeof(line), f)) {
    if (strncmp(line, "VmRSS:", 6) == 0) {
      kb = atol(line + 6);
      break;
    }
  }
  fclose(f);
  return kb;
}

template <typename ClientT>
void RunIterations(ClientT* client, int n) {
  for (int it = 0; it < n; ++it) {
    std::vector<int32_t> input0(16), input1(16);
    for (int i = 0; i < 16; ++i) {
      input0[i] = i + it;
      input1[i] = 2;
    }
    tc::InferInput *in0, *in1;
    CHECK_OK(tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32"));
    CHECK_OK(tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32"));
    CHECK_OK(in0->AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        input0.size() * sizeof(int32_t)));
    CHECK_OK(in1->AppendRaw(
        reinterpret_cast<const uint8_t*>(input1.data()),
        input1.size() * sizeof(int32_t)));
    tc::InferOptions options("simple");
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {in0, in1}));
    const uint8_t* buf;
    size_t len;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
    if (*reinterpret_cast<const int32_t*>(buf) != input0[0] + 2) {
      fprintf(stderr, "FAILED: wrong result at iteration %d\n", it);
      exit(1);
    }
    delete result;
    delete in0;
    delete in1;
  }
}

// BYTES round trips churn the serialize/deserialize buffers (reference
// memory_leak_test loops string models too).
template <typename ClientT>
void RunStringIterations(ClientT* client, int n) {
  for (int it = 0; it < n; ++it) {
    tc::InferInput* in;
    CHECK_OK(tc::InferInput::Create(&in, "INPUT0", {1, 3}, "BYTES"));
    CHECK_OK(in->AppendFromString(
        {"looped", std::string(64, 'x'), std::to_string(it)}));
    tc::InferOptions options("simple_identity");
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, {in}));
    std::vector<std::string> strings;
    CHECK_OK(result->StringData("OUTPUT0", &strings));
    if (strings.size() != 3 || strings[0] != "looped") {
      fprintf(stderr, "FAILED: wrong string result at iteration %d\n", it);
      exit(1);
    }
    delete result;
    delete in;
  }
}

// Stream open/close cycles: reader threads and stream state must be
// reclaimed every cycle.
void RunStreamCycles(const std::string& url, int n) {
  for (int it = 0; it < n; ++it) {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, url));
    std::mutex mu;
    std::condition_variable cv;
    int got = 0;
    CHECK_OK(client->StartStream([&](tc::InferResult* r) {
      std::lock_guard<std::mutex> lk(mu);
      ++got;
      delete r;
      cv.notify_one();
    }));
    int32_t value = it;
    tc::InferInput* in;
    CHECK_OK(tc::InferInput::Create(&in, "INPUT", {1}, "INT32"));
    CHECK_OK(in->AppendRaw(reinterpret_cast<const uint8_t*>(&value), 4));
    tc::InferOptions options("simple_sequence");
    options.sequence_id_ = 100000 + it;
    options.sequence_start_ = true;
    options.sequence_end_ = true;
    CHECK_OK(client->AsyncStreamInfer(options, {in}));
    {
      std::unique_lock<std::mutex> lk(mu);
      cv.wait(lk, [&] { return got >= 1; });
    }
    CHECK_OK(client->FinishStream());
    delete in;
  }
}

// Register/unregister churn on the shm registries.
void RunShmRegisterCycles(tc::InferenceServerHttpClient* client, int n) {
  const char* key = "/leak_region_key";
  shm_unlink(key);
  int fd = shm_open(key, O_RDWR | O_CREAT, 0600);
  if (fd < 0 || ftruncate(fd, 4096) != 0) {
    fprintf(stderr, "FAILED: shm setup\n");
    exit(1);
  }
  for (int it = 0; it < n; ++it) {
    // reuse one key per cycle; server-side registry must not accumulate
    CHECK_OK(client->RegisterSystemSharedMemory("leak_region", key, 4096));
    CHECK_OK(client->UnregisterSystemSharedMemory("leak_region"));
  }
  close(fd);
  shm_unlink(key);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port> [iterations]\n", argv[0]);
    return 2;
  }
  const std::string url = argv[1];
  const int iterations = argc > 2 ? atoi(argv[2]) : 500;
  const std::string grpc_url = argc > 3 ? argv[3] : url;

  std::unique_ptr<tc::InferenceServerHttpClient> http_client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&http_client, url));
  std::unique_ptr<tc::InferenceServerGrpcClient> grpc_client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&grpc_client, grpc_url));

  // warm up: connection pools, lazily-spawned worker threads, allocator
  RunIterations(http_client.get(), 50);
  RunIterations(grpc_client.get(), 50);
  RunStringIterations(http_client.get(), 20);
  RunStreamCycles(url, 5);
  RunShmRegisterCycles(http_client.get(), 20);

  long before_kb = RssKb();
  RunIterations(http_client.get(), iterations);
  RunIterations(grpc_client.get(), iterations);
  RunStringIterations(http_client.get(), iterations / 5);
  RunStringIterations(grpc_client.get(), iterations / 5);
  RunStreamCycles(url, iterations / 25);
  RunShmRegisterCycles(http_client.get(), iterations / 5);
  long after_kb = RssKb();

  long growth_kb = after_kb - before_kb;
  printf("rss before=%ldkB after=%ldkB growth=%ldkB over %d iterations\n",
         before_kb, after_kb, growth_kb, 2 * iterations);
  // steady-state request loops must not accumulate memory; allow modest
  // allocator noise
  if (growth_kb > 8 * 1024) {
    fprintf(stderr, "FAILED: rss grew %ldkB (> 8MB)\n", growth_kb);
    return 1;
  }
  printf("PASS: memory leak test\n");
  return 0;
}
