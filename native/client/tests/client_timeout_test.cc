// Client-timeout matrix (reference model: src/c++/tests/
// client_timeout_test.cc:63-90+ — drive every API with a short deadline
// against custom_identity_int32 and require Deadline Exceeded errors; then
// prove the same calls succeed without the deadline).  The delay comes from
// the model's `execute_delay_ms` request parameter.
//
// Usage: client_timeout_test <http_host:port>

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(expr)                                                \
  do {                                                                \
    tc::Error err__ = (expr);                                         \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,       \
              err__.Message().c_str());                               \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

#define CHECK_TRUE(cond)                                                \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                          \
    }                                                                   \
  } while (false)

namespace {

constexpr uint64_t kShortTimeoutUs = 100 * 1000;  // 100ms deadline...
constexpr const char* kDelayMs = "1500";          // ...vs 1.5s execution

bool IsDeadlineExceeded(const tc::Error& err) {
  return !err.IsOk() &&
         err.Message().find("Deadline Exceeded") != std::string::npos;
}

tc::InferInput* MakeInput(int32_t value) {
  static int32_t storage[8];
  storage[0] = value;
  tc::InferInput* input;
  CHECK_OK(tc::InferInput::Create(&input, "INPUT0", {1, 1}, "INT32"));
  CHECK_OK(input->AppendRaw(
      reinterpret_cast<const uint8_t*>(storage), sizeof(int32_t)));
  return input;
}

tc::InferOptions DelayedOptions(uint64_t client_timeout_us) {
  tc::InferOptions options("custom_identity_int32");
  options.client_timeout_us_ = client_timeout_us;
  options.request_parameters_["execute_delay_ms"] = kDelayMs;
  return options;
}

template <typename ClientT>
void TestSyncTimeout(ClientT* client) {
  tc::InferInput* input = MakeInput(7);
  tc::InferResult* result = nullptr;
  tc::Error err =
      client->Infer(&result, DelayedOptions(kShortTimeoutUs), {input});
  CHECK_TRUE(IsDeadlineExceeded(err));

  // no deadline -> the same slow call completes
  tc::InferOptions patient = DelayedOptions(0);
  patient.request_parameters_["execute_delay_ms"] = "0";
  CHECK_OK(client->Infer(&result, patient, {input}));
  const uint8_t* buf;
  size_t len;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
  CHECK_TRUE(*reinterpret_cast<const int32_t*>(buf) == 7);
  delete result;
  delete input;
}

template <typename ClientT>
void TestAsyncTimeout(ClientT* client) {
  tc::InferInput* input = MakeInput(9);
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  tc::Error async_err;
  CHECK_OK(client->AsyncInfer(
      [&](tc::InferResult* r) {
        std::lock_guard<std::mutex> lk(mu);
        async_err = r->RequestStatus();
        done = true;
        delete r;
        cv.notify_one();
      },
      DelayedOptions(kShortTimeoutUs), {input}));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }
  CHECK_TRUE(IsDeadlineExceeded(async_err));
  delete input;
}

template <typename ClientT>
void TestGenerousDeadlineSucceeds(ClientT* client) {
  // A deadline comfortably above the delay must NOT fire (guards against a
  // deadline clock that starts too early or double-counts pooling time).
  tc::InferInput* input = MakeInput(3);
  tc::InferOptions options("custom_identity_int32");
  options.client_timeout_us_ = 30 * 1000 * 1000;  // 30s
  options.request_parameters_["execute_delay_ms"] = "100";
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, {input}));
  const uint8_t* buf;
  size_t len;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
  CHECK_TRUE(*reinterpret_cast<const int32_t*>(buf) == 3);
  delete result;
  delete input;
}

template <typename ClientT>
void TestPoolShedsDeadline(ClientT* client) {
  // After a deadline fires, the SAME client must serve a normal request:
  // a pooled socket must not inherit the expired deadline (regression for
  // stale SO_RCVTIMEO on reused connections).
  for (int round = 0; round < 3; ++round) {
    tc::InferInput* input = MakeInput(11);
    tc::InferResult* result = nullptr;
    tc::Error err =
        client->Infer(&result, DelayedOptions(kShortTimeoutUs), {input});
    CHECK_TRUE(IsDeadlineExceeded(err));
    tc::InferOptions ok_options("custom_identity_int32");
    CHECK_OK(client->Infer(&result, ok_options, {input}));
    const uint8_t* buf;
    size_t len;
    CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
    CHECK_TRUE(*reinterpret_cast<const int32_t*>(buf) == 11);
    delete result;
    delete input;
  }
}

void TestHttpMultiTimeout(tc::InferenceServerHttpClient* client) {
  // InferMulti: a per-request deadline inside the fan-out must surface as a
  // failed fan-out, not a hang or a partial success silently dropped.
  tc::InferInput* input = MakeInput(5);
  std::vector<std::vector<tc::InferInput*>> multi_inputs(
      2, std::vector<tc::InferInput*>{input});
  std::vector<tc::InferResult*> results;
  tc::Error err = client->InferMulti(
      &results, {DelayedOptions(kShortTimeoutUs)}, multi_inputs);
  if (err.IsOk()) {
    // per-request errors may be delivered on the results instead
    bool any_deadline = false;
    for (auto* r : results) {
      if (IsDeadlineExceeded(r->RequestStatus())) any_deadline = true;
      delete r;
    }
    CHECK_TRUE(any_deadline);
  } else {
    CHECK_TRUE(IsDeadlineExceeded(err));
  }
  delete input;
}

void TestConnectionRefusedSurfacesError() {
  // Nothing listens on this port: the client must return an error quickly
  // (not crash, not hang), under both transports.
  const std::string dead_url = "127.0.0.1:1";
  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    CHECK_OK(tc::InferenceServerHttpClient::Create(&client, dead_url));
    bool live = true;
    tc::Error err = client->IsServerLive(&live);
    CHECK_TRUE(!err.IsOk() || !live);
  }
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, dead_url));
    bool live = true;
    tc::Error err = client->IsServerLive(&live);
    CHECK_TRUE(!err.IsOk() || !live);
  }
}

template <typename ClientT>
void TestZeroTimeoutMeansNoDeadline(ClientT* client) {
  // client_timeout_us == 0 is "no deadline" (reference semantics), even on
  // a slow request.
  tc::InferInput* input = MakeInput(13);
  tc::InferOptions options("custom_identity_int32");
  options.client_timeout_us_ = 0;
  options.request_parameters_["execute_delay_ms"] = "700";
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, {input}));
  delete result;
  delete input;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port>\n", argv[0]);
    return 2;
  }
  const std::string url = argv[1];
  const std::string grpc_url = argc > 2 ? argv[2] : argv[1];

  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
    TestSyncTimeout(client.get());
    TestAsyncTimeout(client.get());
    TestGenerousDeadlineSucceeds(client.get());
    TestPoolShedsDeadline(client.get());
    TestHttpMultiTimeout(client.get());
    TestZeroTimeoutMeansNoDeadline(client.get());
    printf("PASS: http timeouts\n");
  }
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, grpc_url));
    TestSyncTimeout(client.get());
    TestAsyncTimeout(client.get());
    TestGenerousDeadlineSucceeds(client.get());
    TestPoolShedsDeadline(client.get());
    TestZeroTimeoutMeansNoDeadline(client.get());
    printf("PASS: grpc timeouts\n");
  }
  TestConnectionRefusedSurfacesError();
  printf("PASS: connection-refused error surface\n");
  printf("PASS: all\n");
  return 0;
}
