// Client-timeout matrix (reference model: src/c++/tests/
// client_timeout_test.cc:63-90+ — drive every API with a short deadline
// against custom_identity_int32 and require Deadline Exceeded errors; then
// prove the same calls succeed without the deadline).  The delay comes from
// the model's `execute_delay_ms` request parameter.
//
// Usage: client_timeout_test <http_host:port>

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(expr)                                                \
  do {                                                                \
    tc::Error err__ = (expr);                                         \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,       \
              err__.Message().c_str());                               \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

#define CHECK_TRUE(cond)                                                \
  do {                                                                  \
    if (!(cond)) {                                                      \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                          \
    }                                                                   \
  } while (false)

namespace {

constexpr uint64_t kShortTimeoutUs = 100 * 1000;  // 100ms deadline...
constexpr const char* kDelayMs = "1500";          // ...vs 1.5s execution

bool IsDeadlineExceeded(const tc::Error& err) {
  return !err.IsOk() &&
         err.Message().find("Deadline Exceeded") != std::string::npos;
}

tc::InferInput* MakeInput(int32_t value) {
  static int32_t storage[8];
  storage[0] = value;
  tc::InferInput* input;
  CHECK_OK(tc::InferInput::Create(&input, "INPUT0", {1, 1}, "INT32"));
  CHECK_OK(input->AppendRaw(
      reinterpret_cast<const uint8_t*>(storage), sizeof(int32_t)));
  return input;
}

tc::InferOptions DelayedOptions(uint64_t client_timeout_us) {
  tc::InferOptions options("custom_identity_int32");
  options.client_timeout_us_ = client_timeout_us;
  options.request_parameters_["execute_delay_ms"] = kDelayMs;
  return options;
}

template <typename ClientT>
void TestSyncTimeout(ClientT* client) {
  tc::InferInput* input = MakeInput(7);
  tc::InferResult* result = nullptr;
  tc::Error err =
      client->Infer(&result, DelayedOptions(kShortTimeoutUs), {input});
  CHECK_TRUE(IsDeadlineExceeded(err));

  // no deadline -> the same slow call completes
  tc::InferOptions patient = DelayedOptions(0);
  patient.request_parameters_["execute_delay_ms"] = "0";
  CHECK_OK(client->Infer(&result, patient, {input}));
  const uint8_t* buf;
  size_t len;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
  CHECK_TRUE(*reinterpret_cast<const int32_t*>(buf) == 7);
  delete result;
  delete input;
}

template <typename ClientT>
void TestAsyncTimeout(ClientT* client) {
  tc::InferInput* input = MakeInput(9);
  std::mutex mu;
  std::condition_variable cv;
  bool done = false;
  tc::Error async_err;
  CHECK_OK(client->AsyncInfer(
      [&](tc::InferResult* r) {
        std::lock_guard<std::mutex> lk(mu);
        async_err = r->RequestStatus();
        done = true;
        delete r;
        cv.notify_one();
      },
      DelayedOptions(kShortTimeoutUs), {input}));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }
  CHECK_TRUE(IsDeadlineExceeded(async_err));
  delete input;
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port>\n", argv[0]);
    return 2;
  }
  const std::string url = argv[1];

  {
    std::unique_ptr<tc::InferenceServerHttpClient> client;
    CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
    TestSyncTimeout(client.get());
    TestAsyncTimeout(client.get());
    printf("PASS: http timeouts\n");
  }
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, url));
    TestSyncTimeout(client.get());
    TestAsyncTimeout(client.get());
    printf("PASS: grpc timeouts\n");
  }
  printf("PASS: all\n");
  return 0;
}
