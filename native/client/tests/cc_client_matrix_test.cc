// Option/broadcast/data-path matrix against the live harness — the second
// half of the reference's cc_client_test.cc coverage (option and output
// broadcasting for InferMulti, model load with config override, compression
// round trips, decoupled streams, shm data paths, stat accounting;
// reference cc_client_test.cc:300-1350).  Usage: cc_client_matrix_test
// <http_host:port> (gRPC-web rides the same port through the bridge).

#include <dirent.h>
#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"
#include "xla_shm_utils.h"

namespace tc = tc_tpu::client;

namespace {

#define CHECK_OK(expr)                                                   \
  do {                                                                   \
    const tc::Error err__ = (expr);                                      \
    if (!err__.IsOk()) {                                                 \
      fprintf(stderr, "FAILED %s:%d: %s -> %s\n", __FILE__, __LINE__,    \
              #expr, err__.Message().c_str());                           \
      exit(1);                                                           \
    }                                                                    \
  } while (false)

#define CHECK_TRUE(expr)                                                 \
  do {                                                                   \
    if (!(expr)) {                                                       \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #expr);  \
      exit(1);                                                           \
    }                                                                    \
  } while (false)

#define CHECK_ERR(expr)                                                  \
  do {                                                                   \
    const tc::Error err__ = (expr);                                      \
    if (err__.IsOk()) {                                                  \
      fprintf(stderr, "FAILED %s:%d: expected error from %s\n",          \
              __FILE__, __LINE__, #expr);                                \
      exit(1);                                                           \
    }                                                                    \
  } while (false)

std::vector<int32_t> Iota16() {
  std::vector<int32_t> v(16);
  for (int i = 0; i < 16; ++i) v[i] = i;
  return v;
}

void MakeSimpleInputs(
    const std::vector<int32_t>& in0, const std::vector<int32_t>& in1,
    std::vector<tc::InferInput*>* inputs) {
  tc::InferInput *i0, *i1;
  CHECK_OK(tc::InferInput::Create(&i0, "INPUT0", {1, 16}, "INT32"));
  CHECK_OK(tc::InferInput::Create(&i1, "INPUT1", {1, 16}, "INT32"));
  CHECK_OK(i0->AppendRaw(reinterpret_cast<const uint8_t*>(in0.data()),
                         in0.size() * sizeof(int32_t)));
  CHECK_OK(i1->AppendRaw(reinterpret_cast<const uint8_t*>(in1.data()),
                         in1.size() * sizeof(int32_t)));
  inputs->assign({i0, i1});
}

void CheckSum(tc::InferResult* r, const std::vector<int32_t>& in0,
              const std::vector<int32_t>& in1) {
  const uint8_t* buf;
  size_t len;
  CHECK_OK(r->RawData("OUTPUT0", &buf, &len));
  CHECK_TRUE(len == 16 * sizeof(int32_t));
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_TRUE(sums[i] == in0[i] + in1[i]);
}

// -- compression round trips (reference http_client.cc CompressInput) -----
// gRPC endpoint for gRPC clients (real h2c port when given; the
// grpc-web bridge on the HTTP port otherwise)
std::string g_grpc_url;

void TestHttpCompression(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
  auto in0 = Iota16();
  std::vector<int32_t> in1(16, 2);
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferOptions options("simple");
  using CT = tc::InferenceServerHttpClient::CompressionType;
  for (CT req : {CT::NONE, CT::DEFLATE, CT::GZIP}) {
    for (CT resp : {CT::NONE, CT::DEFLATE, CT::GZIP}) {
      tc::InferResult* result;
      CHECK_OK(client->Infer(&result, options, inputs, {}, {}, req, resp));
      CheckSum(result, in0, in1);
      delete result;
    }
  }
  for (auto* i : inputs) delete i;
  printf("PASS: http compression matrix\n");
}

// -- object reuse (reference reuse_infer_objects_client) ------------------
void TestReuseInferObjects(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> hc;
  std::unique_ptr<tc::InferenceServerGrpcClient> gc;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&hc, url));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&gc, g_grpc_url));
  auto in0 = Iota16();
  std::vector<int32_t> in1(16, 5);
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferOptions options("simple");
  options.request_id_ = "reused";
  for (int round = 0; round < 3; ++round) {
    tc::InferResult* r;
    CHECK_OK(hc->Infer(&r, options, inputs));
    CheckSum(r, in0, in1);
    std::string id;
    CHECK_OK(r->Id(&id));
    CHECK_TRUE(id == "reused");
    delete r;
    CHECK_OK(gc->Infer(&r, options, inputs));
    CheckSum(r, in0, in1);
    delete r;
    // rebind fresh data through the same InferInput objects
    CHECK_OK(inputs[0]->Reset());
    for (auto& v : in0) v += 1;
    CHECK_OK(inputs[0]->AppendRaw(
        reinterpret_cast<const uint8_t*>(in0.data()),
        in0.size() * sizeof(int32_t)));
  }
  for (auto* i : inputs) delete i;
  printf("PASS: infer object reuse\n");
}

// -- model control with config override (reference cc_client_test:1202) ---
void TestModelControl() {
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url));
  bool ready = false;
  CHECK_OK(client->IsModelReady(&ready, "identity_fp32"));
  CHECK_TRUE(ready);
  CHECK_OK(client->UnloadModel("identity_fp32"));
  CHECK_OK(client->IsModelReady(&ready, "identity_fp32"));
  CHECK_TRUE(!ready);
  CHECK_OK(client->LoadModel("identity_fp32"));
  CHECK_OK(client->IsModelReady(&ready, "identity_fp32"));
  CHECK_TRUE(ready);
  // load with a config override and verify the served config changed
  const char* cfg =
      "{\"name\": \"identity_fp32\", \"max_batch_size\": 4, \"backend\": "
      "\"jax\"}";
  CHECK_OK(client->LoadModel("identity_fp32", tc::Headers(), cfg));
  tc::pb::ModelConfigResponse mc;
  CHECK_OK(client->ModelConfig(&mc, "identity_fp32"));
  CHECK_TRUE(mc.config().max_batch_size() == 4);
  // restore the original registration for other tests
  CHECK_OK(client->LoadModel("identity_fp32"));
  CHECK_ERR(client->LoadModel("no_such_model_anywhere"));
  printf("PASS: model control with config override\n");
}

// -- BYTES strings through system shm (reference shm string client) -------
void TestStringShm(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
  std::string payload;
  tc::SerializeStringTensor({"ab", "", "xyz"}, &payload);
  const char* key = "/cc_matrix_str_shm";
  shm_unlink(key);
  int fd = shm_open(key, O_RDWR | O_CREAT, 0600);
  CHECK_TRUE(fd >= 0);
  CHECK_TRUE(ftruncate(fd, payload.size()) == 0);
  void* base = mmap(nullptr, payload.size(), PROT_READ | PROT_WRITE,
                    MAP_SHARED, fd, 0);
  CHECK_TRUE(base != MAP_FAILED);
  memcpy(base, payload.data(), payload.size());
  CHECK_OK(client->RegisterSystemSharedMemory("str_region", key,
                                              payload.size()));
  tc::InferInput* input;
  CHECK_OK(tc::InferInput::Create(&input, "INPUT0", {1, 3}, "BYTES"));
  CHECK_OK(input->SetSharedMemory("str_region", payload.size()));
  tc::InferOptions options("simple_identity");
  tc::InferResult* result;
  CHECK_OK(client->Infer(&result, options, {input}));
  std::vector<std::string> strings;
  CHECK_OK(result->StringData("OUTPUT0", &strings));
  CHECK_TRUE(strings.size() == 3);
  CHECK_TRUE(strings[0] == "ab" && strings[1] == "" && strings[2] == "xyz");
  delete result;
  delete input;
  CHECK_OK(client->UnregisterSystemSharedMemory("str_region"));
  munmap(base, payload.size());
  close(fd);
  shm_unlink(key);
  printf("PASS: BYTES via system shm\n");
}

// -- xla-shm offset/status matrix (reference cudashm tests) ---------------
void TestXlaShmMatrix() {
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url));
  const size_t bytes = 64 * sizeof(float);
  tc::XlaShmHandle in_h, out_h;
  CHECK_OK(tc::CreateXlaSharedMemoryRegion(&in_h, "mx_in", bytes, 0));
  CHECK_OK(tc::CreateXlaSharedMemoryRegion(&out_h, "mx_out", bytes, 0));
  std::vector<uint8_t> raw;
  CHECK_OK(tc::GetXlaSharedMemoryRawHandle(in_h, &raw));
  CHECK_OK(client->RegisterCudaSharedMemory("mx_in", raw, 0, bytes));
  CHECK_OK(tc::GetXlaSharedMemoryRawHandle(out_h, &raw));
  CHECK_OK(client->RegisterCudaSharedMemory("mx_out", raw, 0, bytes));

  // registering the same name again must fail
  CHECK_ERR(client->RegisterCudaSharedMemory("mx_in", raw, 0, bytes));

  // offset write: fill halves with two writes, then infer on the region
  std::vector<float> lo(32, 1.5f), hi(32, -2.5f);
  CHECK_OK(tc::SetXlaSharedMemoryRegion(in_h, lo.data(), bytes / 2, 0));
  CHECK_OK(tc::SetXlaSharedMemoryRegion(in_h, hi.data(), bytes / 2,
                                        bytes / 2));
  tc::InferInput* input;
  CHECK_OK(tc::InferInput::Create(&input, "INPUT0", {1, 64}, "FP32"));
  CHECK_OK(input->SetSharedMemory("mx_in", bytes));
  tc::InferRequestedOutput* out;
  CHECK_OK(tc::InferRequestedOutput::Create(&out, "OUTPUT0"));
  CHECK_OK(out->SetSharedMemory("mx_out", bytes));
  tc::InferOptions options("identity_fp32");
  tc::InferResult* result;
  CHECK_OK(client->Infer(&result, options, {input}, {out}));
  delete result;
  std::vector<float> got(64);
  CHECK_OK(tc::GetXlaSharedMemoryContents(out_h, got.data(), bytes));
  for (int i = 0; i < 32; ++i) CHECK_TRUE(got[i] == 1.5f);
  for (int i = 32; i < 64; ++i) CHECK_TRUE(got[i] == -2.5f);

  // status lists both regions; unregister-one removes exactly one
  tc::pb::CudaSharedMemoryStatusResponse status;
  CHECK_OK(client->CudaSharedMemoryStatus(&status));
  CHECK_TRUE(status.regions().count("mx_in") == 1);
  CHECK_TRUE(status.regions().count("mx_out") == 1);
  CHECK_TRUE(status.regions().at("mx_in").byte_size() == bytes);
  CHECK_OK(client->UnregisterCudaSharedMemory("mx_in"));
  CHECK_OK(client->CudaSharedMemoryStatus(&status));
  CHECK_TRUE(status.regions().count("mx_in") == 0);
  CHECK_TRUE(status.regions().count("mx_out") == 1);
  CHECK_OK(client->UnregisterCudaSharedMemory("mx_out"));

  // inferring against an unregistered region must fail
  tc::InferResult* bad = nullptr;
  CHECK_ERR(client->Infer(&bad, options, {input}, {out}));

  delete input;
  delete out;
  CHECK_OK(tc::DestroyXlaSharedMemoryRegion(&in_h));
  CHECK_OK(tc::DestroyXlaSharedMemoryRegion(&out_h));
  printf("PASS: xla shm offset/status matrix\n");
}

// -- decoupled stream: N responses per request (reference repeat) ---------
void TestDecoupledRepeat() {
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url));
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> outs;
  std::vector<uint32_t> idxs;
  int finals = 0;
  CHECK_OK(client->StartStream([&](tc::InferResult* r) {
    std::lock_guard<std::mutex> lk(mu);
    bool is_final = false, is_null = false;
    r->IsFinalResponse(&is_final);
    r->IsNullResponse(&is_null);
    if (is_final) ++finals;
    const uint8_t* buf;
    size_t len;
    if (!is_null && r->RequestStatus().IsOk() &&
        r->RawData("OUT", &buf, &len).IsOk() && len >= 4) {
      int32_t v;
      memcpy(&v, buf, 4);
      outs.push_back(v);
      if (r->RawData("IDX", &buf, &len).IsOk() && len >= 4) {
        uint32_t ix;
        memcpy(&ix, buf, 4);
        idxs.push_back(ix);
      }
    }
    cv.notify_all();
    delete r;
  }));
  std::vector<int32_t> values{4, 7, 9};
  std::vector<uint32_t> delays{1000, 1000, 1000};
  uint32_t wait = 0;
  tc::InferInput *vin, *din, *win;
  CHECK_OK(tc::InferInput::Create(&vin, "IN", {3}, "INT32"));
  CHECK_OK(vin->AppendRaw(reinterpret_cast<const uint8_t*>(values.data()),
                          values.size() * sizeof(int32_t)));
  CHECK_OK(tc::InferInput::Create(&din, "DELAY", {3}, "UINT32"));
  CHECK_OK(din->AppendRaw(reinterpret_cast<const uint8_t*>(delays.data()),
                          delays.size() * sizeof(uint32_t)));
  CHECK_OK(tc::InferInput::Create(&win, "WAIT", {1}, "UINT32"));
  CHECK_OK(win->AppendRaw(reinterpret_cast<const uint8_t*>(&wait),
                          sizeof(uint32_t)));
  tc::InferOptions options("repeat_int32");
  options.triton_enable_empty_final_response_ = true;
  CHECK_OK(client->AsyncStreamInfer(options, {vin, din, win}));
  {
    std::unique_lock<std::mutex> lk(mu);
    CHECK_TRUE(cv.wait_for(lk, std::chrono::seconds(60), [&] {
      return outs.size() == 3 && finals >= 1;
    }));
  }
  CHECK_OK(client->FinishStream());
  CHECK_TRUE(outs[0] == 4 && outs[1] == 7 && outs[2] == 9);
  CHECK_TRUE(idxs.size() == 3 && idxs[0] == 0 && idxs[2] == 2);
  delete vin;
  delete din;
  delete win;
  printf("PASS: decoupled repeat stream (finals=%d)\n", finals);
}

// -- InferMulti output/option broadcast arity matrix ----------------------
void TestMultiBroadcast(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
  auto in0 = Iota16();
  std::vector<int32_t> in1(16, 3);
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferRequestedOutput *o0, *o1;
  CHECK_OK(tc::InferRequestedOutput::Create(&o0, "OUTPUT0"));
  CHECK_OK(tc::InferRequestedOutput::Create(&o1, "OUTPUT1"));
  std::vector<std::vector<tc::InferInput*>> multi_inputs(4, inputs);
  tc::InferOptions options("simple");

  // one options + one outputs-set broadcast across all four requests
  {
    std::vector<tc::InferResult*> results;
    CHECK_OK(client->InferMulti(&results, {options}, multi_inputs, {{o0, o1}}));
    CHECK_TRUE(results.size() == 4);
    for (auto* r : results) {
      CheckSum(r, in0, in1);
      delete r;
    }
  }
  // per-request options vector of matching arity
  {
    std::vector<tc::InferOptions> opts(4, options);
    for (size_t i = 0; i < opts.size(); ++i)
      opts[i].request_id_ = "multi-" + std::to_string(i);
    std::vector<tc::InferResult*> results;
    CHECK_OK(client->InferMulti(&results, opts, multi_inputs));
    CHECK_TRUE(results.size() == 4);
    for (size_t i = 0; i < results.size(); ++i) {
      std::string id;
      CHECK_OK(results[i]->Id(&id));
      CHECK_TRUE(id == "multi-" + std::to_string(i));
      delete results[i];
    }
  }
  // arity mismatches must be rejected: 2 options / 3 outputs for 4 requests
  {
    std::vector<tc::InferResult*> results;
    CHECK_ERR(client->InferMulti(&results, {options, options}, multi_inputs));
    CHECK_ERR(client->InferMulti(&results, {options}, multi_inputs,
                                 {{o0}, {o1}, {o0, o1}}));
    std::vector<std::vector<tc::InferInput*>> empty_inputs;
    CHECK_ERR(client->InferMulti(&results, {options}, empty_inputs));
  }
  for (auto* i : inputs) delete i;
  delete o0;
  delete o1;
  printf("PASS: InferMulti broadcast arity matrix\n");
}

// -- sequence over HTTP unary (reference sequence_sync clients) -----------
void TestSequenceHttpSync(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));
  std::vector<int32_t> acc;
  std::vector<int32_t> values{2, 4, 6};
  for (size_t i = 0; i < values.size(); ++i) {
    tc::InferInput* in;
    CHECK_OK(tc::InferInput::Create(&in, "INPUT", {1}, "INT32"));
    CHECK_OK(in->AppendRaw(reinterpret_cast<const uint8_t*>(&values[i]),
                           sizeof(int32_t)));
    tc::InferOptions options("simple_sequence");
    options.sequence_id_ = 4242;
    options.sequence_start_ = (i == 0);
    options.sequence_end_ = (i == values.size() - 1);
    tc::InferResult* r;
    CHECK_OK(client->Infer(&r, options, {in}));
    const uint8_t* buf;
    size_t len;
    CHECK_OK(r->RawData("OUTPUT", &buf, &len));
    int32_t v;
    memcpy(&v, buf, 4);
    acc.push_back(v);
    delete r;
    delete in;
  }
  CHECK_TRUE(acc[0] == 2 && acc[1] == 6 && acc[2] == 12);
  // a sequence request without a correlation id must be rejected
  tc::InferInput* in;
  int32_t one = 1;
  CHECK_OK(tc::InferInput::Create(&in, "INPUT", {1}, "INT32"));
  CHECK_OK(in->AppendRaw(reinterpret_cast<const uint8_t*>(&one), 4));
  tc::InferOptions bad("simple_sequence");
  tc::InferResult* r = nullptr;
  CHECK_ERR(client->Infer(&r, bad, {in}));
  delete in;
  printf("PASS: sequence over http unary\n");
}

// -- client stat accounting (reference InferStat/UpdateInferStat) ---------
size_t CountSocketFds() {
  size_t n = 0;
  DIR* d = opendir("/proc/self/fd");
  if (d == nullptr) return 0;
  for (dirent* e = readdir(d); e != nullptr; e = readdir(d)) {
    char path[300], target[64];
    snprintf(path, sizeof(path), "/proc/self/fd/%s", e->d_name);
    ssize_t len = readlink(path, target, sizeof(target) - 1);
    if (len > 0) {
      target[len] = '\0';
      if (strncmp(target, "socket:", 7) == 0) ++n;
    }
  }
  closedir(d);
  return n;
}

// Concurrent unary RPCs multiplex over ONE socket (grpc++ channel parity,
// reference grpc_client.cc:47-152): 12 threads x 8 calls on one client
// must not open a connection per caller.
void TestUnaryMux() {
  const char* transport = getenv("TC_TPU_GRPC_TRANSPORT");
  if (transport != nullptr && std::string(transport) == "web") {
    return;  // web bridge pools HTTP/1.1 sockets; mux is an h2 feature
  }
  const char* mux = getenv("TC_TPU_GRPC_UNARY_MUX");
  if (mux != nullptr && std::string(mux) == "0") return;
  size_t before = CountSocketFds();
  {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url));
    constexpr int kThreads = 12, kCallsPerThread = 8;
    std::vector<std::thread> threads;
    std::vector<int> failures(kThreads, 0);
    for (int t = 0; t < kThreads; ++t) {
      threads.emplace_back([&client, &failures, t] {
        for (int i = 0; i < kCallsPerThread; ++i) {
          std::vector<int32_t> in0 = Iota16(), in1 = Iota16();
          std::vector<tc::InferInput*> inputs;
          tc::InferOptions options("simple");
          MakeSimpleInputs(in0, in1, &inputs);
          tc::InferResult* result = nullptr;
          tc::Error err = client->Infer(&result, options, inputs);
          if (err.IsOk()) {
            CheckSum(result, in0, in1);
          } else {
            failures[t]++;
          }
          delete result;
          for (auto* in : inputs) delete in;
        }
      });
    }
    for (auto& th : threads) th.join();
    for (int t = 0; t < kThreads; ++t) CHECK_TRUE(failures[t] == 0);
    // all 96 calls in flight shared the multiplexed channel: at most the
    // one mux socket (+1 slack for a transient probe) — NOT one per caller
    size_t during = CountSocketFds();
    CHECK_TRUE(during <= before + 2);  // unsigned-safe even if an earlier
                                       // test's cached socket closed
  }
  printf("PASS: unary mux (single-socket concurrency)\n");
}

void TestInferStatAccounting() {
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url));
  tc::InferStat before, after;
  CHECK_OK(client->ClientInferStat(&before));
  auto in0 = Iota16();
  std::vector<int32_t> in1(16, 1);
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferOptions options("simple");
  const int kN = 5;
  for (int i = 0; i < kN; ++i) {
    tc::InferResult* r;
    CHECK_OK(client->Infer(&r, options, inputs));
    delete r;
  }
  CHECK_OK(client->ClientInferStat(&after));
  CHECK_TRUE(after.completed_request_count ==
             before.completed_request_count + kN);
  CHECK_TRUE(after.cumulative_total_request_time_ns >
             before.cumulative_total_request_time_ns);
  CHECK_TRUE(after.cumulative_send_time_ns >= before.cumulative_send_time_ns);
  for (auto* i : inputs) delete i;
  printf("PASS: client InferStat accounting\n");
}

}  // namespace

// -- channel options: keepalive + message-size caps (reference
// KeepAliveOptions grpc_client.h:62-86, grpc::ChannelArguments usage in
// simple_grpc_custom_args_client.cc) --------------------------------------
void TestChannelSharing() {
  // reference channel cache (grpc_client.cc:47-152,
  // TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT default 6): clients of the
  // same url share one transport; customized clients get private ones
  std::unique_ptr<tc::InferenceServerGrpcClient> a, b, c;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&a, g_grpc_url));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&b, g_grpc_url));
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&c, g_grpc_url));
  // 3 clients + the cache entry own the shared transport
  CHECK_TRUE(a->TransportUseCount() >= 4);
  CHECK_TRUE(b->TransportUseCount() >= 4);
  // shared transport serves all of them
  for (auto* cl : {a.get(), b.get(), c.get()}) {
    bool live = false;
    CHECK_OK(cl->IsServerLive(&live));
    CHECK_TRUE(live);
  }
  // opt-out gets a private transport
  std::unique_ptr<tc::InferenceServerGrpcClient> priv;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &priv, g_grpc_url, false, /*use_cached_channel=*/false));
  CHECK_TRUE(priv->TransportUseCount() == 1);
  // keepalive-customized clients never share (options mutate transports)
  tc::KeepAliveOptions ka;
  ka.keepalive_time_ms = 5000;
  std::unique_ptr<tc::InferenceServerGrpcClient> kac;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&kac, g_grpc_url, false,
                                                 ka));
  CHECK_TRUE(kac->TransportUseCount() == 1);
  // releasing all shared clients empties the cache entry; the next client
  // builds a fresh shared transport (count = client + cache)
  a.reset();
  b.reset();
  c.reset();
  std::unique_ptr<tc::InferenceServerGrpcClient> d;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&d, g_grpc_url));
  CHECK_TRUE(d->TransportUseCount() == 2);
  printf("PASS: channel sharing cache\n");
}

void TestChannelOptions() {
  // keepalive-configured client behaves identically for unary RPCs
  {
    tc::KeepAliveOptions ka;
    ka.keepalive_time_ms = 5000;
    ka.keepalive_timeout_ms = 1000;
    ka.keepalive_permit_without_calls = true;
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url, false, ka));
    auto in0 = Iota16();
    std::vector<int32_t> in1(16, 1);
    std::vector<tc::InferInput*> inputs;
    MakeSimpleInputs(in0, in1, &inputs);
    tc::InferOptions options("simple");
    tc::InferResult* result = nullptr;
    CHECK_OK(client->Infer(&result, options, inputs));
    CheckSum(result, in0, in1);
    delete result;
    for (auto* in : inputs) delete in;
  }
  // a generous receive cap passes; a tiny one rejects with a clear error
  for (int cap : {1 << 20, 64}) {
    tc::ChannelArguments args;
    args.SetMaxReceiveMessageSize(cap);
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url, args));
    auto in0 = Iota16();
    std::vector<int32_t> in1(16, 1);
    std::vector<tc::InferInput*> inputs;
    MakeSimpleInputs(in0, in1, &inputs);
    tc::InferOptions options("simple");
    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, inputs);
    if (cap >= (1 << 20)) {
      CHECK_OK(err);
      CheckSum(result, in0, in1);
      delete result;
    } else {
      CHECK_ERR(err);
      CHECK_TRUE(err.Message().find("maximum receive message size") !=
                 std::string::npos);
    }
    for (auto* in : inputs) delete in;
  }
  // the send cap rejects oversized request bodies client-side
  {
    tc::ChannelArguments args;
    args.SetMaxSendMessageSize(16);
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url, args));
    auto in0 = Iota16();
    std::vector<int32_t> in1(16, 1);
    std::vector<tc::InferInput*> inputs;
    MakeSimpleInputs(in0, in1, &inputs);
    tc::InferOptions options("simple");
    tc::InferResult* result = nullptr;
    tc::Error err = client->Infer(&result, options, inputs);
    CHECK_ERR(err);
    CHECK_TRUE(err.Message().find("maximum send message size") !=
               std::string::npos);
    for (auto* in : inputs) delete in;
  }
  // keepalive settings survive onto the duplex stream path: a streaming
  // sequence still works with keepalive probes armed
  {
    tc::KeepAliveOptions ka;
    ka.keepalive_time_ms = 5000;
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, g_grpc_url, false, ka));
    std::mutex mu;
    std::condition_variable cv;
    std::vector<int32_t> got;
    CHECK_OK(client->StartStream([&](tc::InferResult* r) {
      const uint8_t* buf;
      size_t len;
      if (r->RequestStatus().IsOk() && r->RawData("OUTPUT", &buf, &len).IsOk()
          && len >= 4) {
        int32_t v;
        memcpy(&v, buf, 4);
        std::lock_guard<std::mutex> lk(mu);
        got.push_back(v);
        cv.notify_all();
      } else {
        // surface the server's error immediately instead of burning the
        // 30s wait and failing with only the count mismatch
        fprintf(stderr, "stream result error: %s\n",
                r->RequestStatus().Message().c_str());
      }
      delete r;
    }));
    for (int step = 0; step < 3; ++step) {
      tc::InferInput* in;
      int32_t v = step + 1;
      CHECK_OK(tc::InferInput::Create(&in, "INPUT", {1}, "INT32"));
      CHECK_OK(in->AppendRaw(reinterpret_cast<const uint8_t*>(&v), 4));
      tc::InferOptions options("simple_sequence");
      options.sequence_id_ = 4242;
      options.sequence_start_ = (step == 0);
      options.sequence_end_ = (step == 2);
      CHECK_OK(client->AsyncStreamInfer(options, {in}));
      delete in;
    }
    {
      std::unique_lock<std::mutex> lk(mu);
      CHECK_TRUE(cv.wait_for(lk, std::chrono::seconds(30),
                             [&] { return got.size() >= 3; }));
    }
    CHECK_OK(client->FinishStream());
    CHECK_TRUE(got.back() == 1 + 2 + 3);  // accumulator semantics
  }
}

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port> [grpc_host:port]\n",
            argv[0]);
    return 2;
  }
  const std::string url = argv[1];
  g_grpc_url = argc > 2 ? argv[2] : argv[1];
  TestChannelSharing();
  TestChannelOptions();
  TestHttpCompression(url);
  TestReuseInferObjects(url);
  TestModelControl();
  TestStringShm(url);
  TestXlaShmMatrix();
  TestDecoupledRepeat();
  TestMultiBroadcast(url);
  TestSequenceHttpSync(url);
  TestInferStatAccounting();
  TestUnaryMux();
  printf("PASS: all\n");
  return 0;
}
