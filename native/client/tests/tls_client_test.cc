// Secure-transport round trip for the native clients: HTTPS unary infer
// (HTTP client, CA-pinned + hostname verification) and secure gRPC-Web
// (gRPC client over TLS, unary + duplex stream) against the harness's TLS
// frontends.  Also proves verification is real: an untrusted CA must be
// rejected.
//
// usage: tls_client_test <https_host:port> <ca_pem_path> [cert] [key]
//        [grpcs_host:port]   (the stock secure gRPC port: real grpcs via
//                             TLS+ALPN h2; the https port exercises the
//                             gRPC-Web-over-TLS fallback)

#include <cstdio>
#include <cstring>
#include <queue>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(x)                                                   \
  do {                                                                \
    tc::Error err__ = (x);                                            \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s -> %s\n", __FILE__, __LINE__, \
              #x, err__.Message().c_str());                           \
      exit(1);                                                        \
    }                                                                 \
  } while (0)

#define CHECK_TRUE(x)                                                  \
  do {                                                                 \
    if (!(x)) {                                                        \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #x);   \
      exit(1);                                                         \
    }                                                                  \
  } while (0)

namespace {

void MakeSimpleInputs(
    std::vector<int32_t>& in0, std::vector<int32_t>& in1,
    std::vector<tc::InferInput*>* inputs) {
  tc::InferInput *i0, *i1;
  CHECK_OK(tc::InferInput::Create(&i0, "INPUT0", {1, 16}, "INT32"));
  CHECK_OK(tc::InferInput::Create(&i1, "INPUT1", {1, 16}, "INT32"));
  CHECK_OK(i0->AppendRaw(reinterpret_cast<uint8_t*>(in0.data()),
                         in0.size() * sizeof(int32_t)));
  CHECK_OK(i1->AppendRaw(reinterpret_cast<uint8_t*>(in1.data()),
                         in1.size() * sizeof(int32_t)));
  inputs->assign({i0, i1});
}

void CheckSum(tc::InferResult* result, const std::vector<int32_t>& in0,
              const std::vector<int32_t>& in1) {
  const uint8_t* buf;
  size_t len;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &len));
  CHECK_TRUE(len == in0.size() * sizeof(int32_t));
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (size_t i = 0; i < in0.size(); ++i) {
    CHECK_TRUE(sum[i] == in0[i] + in1[i]);
  }
}

void TestHttpsInfer(const std::string& url, const std::string& ca) {
  tc::HttpSslOptions ssl;
  ssl.ca_info = ca;
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(
      &client, url, false, 4, /*use_ssl=*/true, ssl));
  bool live = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK_TRUE(live);
  std::vector<int32_t> in0(16), in1(16, 3);
  for (int i = 0; i < 16; ++i) in0[i] = i;
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, inputs));
  CheckSum(result, in0, in1);
  delete result;
  for (auto* in : inputs) delete in;
  printf("PASS: https unary infer (CA-pinned)\n");
}

void TestHttpsRejectsUntrustedCa(const std::string& url) {
  // default trust store does not contain the harness's self-signed cert
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(
      &client, url, false, 4, /*use_ssl=*/true, tc::HttpSslOptions()));
  bool live = false;
  tc::Error err = client->IsServerLive(&live);
  CHECK_TRUE(!err.IsOk());
  CHECK_TRUE(err.Message().find("TLS handshake") != std::string::npos);
  printf("PASS: https rejects untrusted CA\n");
}

void TestClientCertPlumbing(const std::string& url, const std::string& ca,
                            const std::string& cert,
                            const std::string& key) {
  // exercises the client cert/key file-loading paths (SSL_CTX_use_*).  The
  // harness doesn't REQUEST a client certificate, so this proves loading +
  // handshake compatibility, not server-side mTLS verification.
  tc::HttpSslOptions ssl;
  ssl.ca_info = ca;
  ssl.cert = cert;
  ssl.key = key;
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(
      &client, url, false, 4, /*use_ssl=*/true, ssl));
  bool live = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK_TRUE(live);
  // a bad key path must fail at Create (context build), not first request
  tc::HttpSslOptions bad = ssl;
  bad.key = "/nonexistent/key.pem";
  std::unique_ptr<tc::InferenceServerHttpClient> bad_client;
  tc::Error err = tc::InferenceServerHttpClient::Create(
      &bad_client, url, false, 4, /*use_ssl=*/true, bad);
  CHECK_TRUE(!err.IsOk());
  CHECK_TRUE(err.Message().find("client key") != std::string::npos);
  printf("PASS: client cert/key loading\n");
}

void TestSecureGrpc(const std::string& url, const std::string& ca,
                    const char* label) {
  tc::InferenceServerGrpcClient::GrpcSslOptions ssl;
  ssl.root_certificates = ca;
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(
      &client, url, false, /*use_ssl=*/true, ssl));
  bool ready = false;
  CHECK_OK(client->IsServerReady(&ready));
  CHECK_TRUE(ready);
  std::vector<int32_t> in0(16, 5), in1(16, 2);
  std::vector<tc::InferInput*> inputs;
  MakeSimpleInputs(in0, in1, &inputs);
  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, inputs));
  CheckSum(result, in0, in1);
  delete result;

  // duplex stream over TLS
  std::queue<tc::InferResult*> results;
  CHECK_OK(client->StartStream(
      [&results](tc::InferResult* r) { results.push(r); }));
  CHECK_OK(client->AsyncStreamInfer(options, inputs));
  CHECK_OK(client->FinishStream());
  CHECK_TRUE(results.size() == 1);
  CheckSum(results.front(), in0, in1);
  delete results.front();
  for (auto* in : inputs) delete in;
  printf("PASS: secure grpc unary + stream (%s)\n", label);
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <https_host:port> <ca_pem_path>\n", argv[0]);
    return 2;
  }
  const std::string url = argv[1];
  const std::string ca = argv[2];
  TestHttpsInfer(url, ca);
  TestHttpsRejectsUntrustedCa(url);
  if (argc >= 5) TestClientCertPlumbing(url, ca, argv[3], argv[4]);
  TestSecureGrpc(url, ca, "web-over-TLS fallback via https port");
  if (argc >= 6) TestSecureGrpc(argv[5], ca, "real grpcs: TLS + ALPN h2");
  printf("PASS: all\n");
  return 0;
}
