// C++ client integration test (reference model: src/c++/tests/
// cc_client_test.cc:38-44 — "must be run with a running server"; here the
// python test harness spins the server and runs this binary, so the test is
// hermetic).  assert-style checks, no gtest dependency in the image.
//
// Usage: cc_client_test <http_host:port>

#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <condition_variable>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"
#include "json.h"

namespace tc = tc_tpu::client;

#define CHECK_OK(expr)                                                \
  do {                                                                \
    tc::Error err__ = (expr);                                         \
    if (!err__.IsOk()) {                                              \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__,       \
              err__.Message().c_str());                               \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

#define CHECK_TRUE(cond)                                              \
  do {                                                                \
    if (!(cond)) {                                                    \
      fprintf(stderr, "FAILED %s:%d: %s\n", __FILE__, __LINE__, #cond); \
      exit(1);                                                        \
    }                                                                 \
  } while (false)

namespace {

void PrepareSimpleInputs(
    std::vector<int32_t>* input0, std::vector<int32_t>* input1,
    std::vector<tc::InferInput*>* inputs) {
  input0->resize(16);
  input1->resize(16);
  for (int i = 0; i < 16; ++i) {
    (*input0)[i] = i;
    (*input1)[i] = 1;
  }
  tc::InferInput* in0;
  tc::InferInput* in1;
  CHECK_OK(tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32"));
  CHECK_OK(tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32"));
  CHECK_OK(in0->AppendRaw(
      reinterpret_cast<const uint8_t*>(input0->data()),
      input0->size() * sizeof(int32_t)));
  CHECK_OK(in1->AppendRaw(
      reinterpret_cast<const uint8_t*>(input1->data()),
      input1->size() * sizeof(int32_t)));
  inputs->push_back(in0);
  inputs->push_back(in1);
}

void CheckSimpleResult(
    tc::InferResult* result, const std::vector<int32_t>& input0,
    const std::vector<int32_t>& input1) {
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  CHECK_OK(result->RawData("OUTPUT0", &buf, &byte_size));
  CHECK_TRUE(byte_size == 16 * sizeof(int32_t));
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_TRUE(sum[i] == input0[i] + input1[i]);
  CHECK_OK(result->RawData("OUTPUT1", &buf, &byte_size));
  const int32_t* diff = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) CHECK_TRUE(diff[i] == input0[i] - input1[i]);
  std::vector<int64_t> shape;
  CHECK_OK(result->Shape("OUTPUT0", &shape));
  CHECK_TRUE(shape.size() == 2 && shape[0] == 1 && shape[1] == 16);
  std::string datatype;
  CHECK_OK(result->Datatype("OUTPUT0", &datatype));
  CHECK_TRUE(datatype == "INT32");
}

void TestHttp(const std::string& url) {
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  CHECK_OK(tc::InferenceServerHttpClient::Create(&client, url));

  bool live = false, ready = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK_TRUE(live);
  CHECK_OK(client->IsServerReady(&ready));
  CHECK_TRUE(ready);
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK_TRUE(ready);

  std::string metadata;
  CHECK_OK(client->ServerMetadata(&metadata));
  CHECK_TRUE(metadata.find("extensions") != std::string::npos);
  CHECK_OK(client->ModelMetadata(&metadata, "simple"));
  tc_tpu::json::Value doc;
  std::string jerr;
  CHECK_TRUE(tc_tpu::json::Parse(metadata, &doc, &jerr));
  CHECK_TRUE(doc.At("name").AsString() == "simple");
  CHECK_OK(client->ModelConfig(&metadata, "simple"));
  CHECK_OK(client->ModelRepositoryIndex(&metadata));
  CHECK_TRUE(metadata.find("simple") != std::string::npos);

  // sync infer
  std::vector<int32_t> input0, input1;
  std::vector<tc::InferInput*> inputs;
  PrepareSimpleInputs(&input0, &input1, &inputs);
  tc::InferRequestedOutput* out0;
  tc::InferRequestedOutput* out1;
  CHECK_OK(tc::InferRequestedOutput::Create(&out0, "OUTPUT0"));
  CHECK_OK(tc::InferRequestedOutput::Create(&out1, "OUTPUT1"));
  std::vector<const tc::InferRequestedOutput*> outputs{out0, out1};

  tc::InferOptions options("simple");
  options.request_id_ = "42";
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, inputs, outputs));
  CheckSimpleResult(result, input0, input1);
  std::string id;
  CHECK_OK(result->Id(&id));
  CHECK_TRUE(id == "42");
  delete result;

  // async infer
  std::mutex mu;
  std::condition_variable cv;
  tc::InferResult* async_result = nullptr;
  bool done = false;
  CHECK_OK(client->AsyncInfer(
      [&](tc::InferResult* r) {
        std::lock_guard<std::mutex> lk(mu);
        async_result = r;
        done = true;
        cv.notify_one();
      },
      options, inputs, outputs));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }
  CHECK_OK(async_result->RequestStatus());
  CheckSimpleResult(async_result, input0, input1);
  delete async_result;

  // BYTES round trip via simple_identity
  tc::InferInput* sin;
  CHECK_OK(tc::InferInput::Create(&sin, "INPUT0", {1, 3}, "BYTES"));
  CHECK_OK(sin->AppendFromString({"alpha", "", "gamma"}));
  tc::InferResult* sresult = nullptr;
  tc::InferOptions soptions("simple_identity");
  CHECK_OK(client->Infer(&sresult, soptions, {sin}));
  std::vector<std::string> strings;
  CHECK_OK(sresult->StringData("OUTPUT0", &strings));
  CHECK_TRUE(strings.size() == 3);
  CHECK_TRUE(strings[0] == "alpha" && strings[1].empty() &&
             strings[2] == "gamma");
  delete sresult;
  delete sin;

  // body compression round trips (zlib: gzip + deflate request coding;
  // gzip response negotiated via Accept-Encoding)
  using CT = tc::InferenceServerHttpClient::CompressionType;
  for (CT req_comp : {CT::GZIP, CT::DEFLATE}) {
    tc::InferResult* cresult = nullptr;
    CHECK_OK(client->Infer(
        &cresult, options, inputs, outputs, tc::Headers(), req_comp,
        CT::GZIP));
    CheckSimpleResult(cresult, input0, input1);
    delete cresult;
  }

  // InferMulti: broadcast options over 3 requests
  {
    std::vector<std::vector<tc::InferInput*>> multi_inputs(3, inputs);
    std::vector<tc::InferResult*> results;
    CHECK_OK(client->InferMulti(&results, {options}, multi_inputs));
    CHECK_TRUE(results.size() == 3);
    for (auto* r : results) {
      CheckSimpleResult(r, input0, input1);
      delete r;
    }
    // arity mismatch must be rejected (2 options vs 3 requests)
    tc::Error multi_err = client->InferMulti(
        &results, {options, options}, multi_inputs);
    CHECK_TRUE(!multi_err.IsOk());

    // AsyncInferMulti: one callback with results in request order
    std::mutex mmu;
    std::condition_variable mcv;
    bool mdone = false;
    std::vector<tc::InferResult*> mresults;
    CHECK_OK(client->AsyncInferMulti(
        [&](std::vector<tc::InferResult*> rs) {
          std::lock_guard<std::mutex> lk(mmu);
          mresults = std::move(rs);
          mdone = true;
          mcv.notify_one();
        },
        {options}, multi_inputs));
    {
      std::unique_lock<std::mutex> lk(mmu);
      mcv.wait(lk, [&] { return mdone; });
    }
    CHECK_TRUE(mresults.size() == 3);
    for (auto* r : mresults) {
      CHECK_OK(r->RequestStatus());
      CheckSimpleResult(r, input0, input1);
      delete r;
    }
  }

  // TLS (tls_client_test covers the full round trip): with libssl present,
  // a use_ssl client against a PLAINTEXT port must fail the handshake —
  // never silently downgrade to http; without libssl, Create must fail
  // loudly instead
  {
    std::unique_ptr<tc::InferenceServerHttpClient> ssl_client;
    tc::Error create_err = tc::InferenceServerHttpClient::Create(
        &ssl_client, url, false, 4, true);
    if (create_err.IsOk()) {
      bool live = false;
      tc::Error ssl_err = ssl_client->IsServerLive(&live);
      CHECK_TRUE(!ssl_err.IsOk());
    } else {
      CHECK_TRUE(create_err.Message().find("TLS unavailable") !=
                 std::string::npos);
    }
  }

  // trace/log settings management
  {
    std::string settings;
    CHECK_OK(client->GetTraceSettings(&settings));
    CHECK_TRUE(settings.find("trace_level") != std::string::npos);
    CHECK_OK(client->UpdateTraceSettings(
        &settings, "", {{"trace_level", {"TIMESTAMPS"}}}));
    CHECK_TRUE(settings.find("TIMESTAMPS") != std::string::npos);
    CHECK_OK(client->UpdateTraceSettings(
        &settings, "", {{"trace_level", {"OFF"}}}));
    CHECK_OK(client->GetLogSettings(&settings));
    CHECK_TRUE(settings.find("log_verbose_level") != std::string::npos);
  }

  // error matrix: unknown model / unknown input / shape mismatch / missing
  {
    tc::InferResult* bad = nullptr;
    tc::InferOptions bad_options("no_such_model");
    tc::Error err = client->Infer(&bad, bad_options, inputs, outputs);
    CHECK_TRUE(!err.IsOk());

    tc::InferInput* wrong_name;
    CHECK_OK(tc::InferInput::Create(&wrong_name, "NOPE", {1, 16}, "INT32"));
    CHECK_OK(wrong_name->AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        input0.size() * sizeof(int32_t)));
    err = client->Infer(&bad, options, {wrong_name, inputs[1]}, outputs);
    CHECK_TRUE(!err.IsOk());
    CHECK_TRUE(err.Message().find("NOPE") != std::string::npos);
    delete wrong_name;

    tc::InferInput* wrong_shape;
    CHECK_OK(tc::InferInput::Create(&wrong_shape, "INPUT0", {1, 8}, "INT32"));
    CHECK_OK(wrong_shape->AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()), 8 * sizeof(int32_t)));
    err = client->Infer(&bad, options, {wrong_shape, inputs[1]}, outputs);
    CHECK_TRUE(!err.IsOk());
    delete wrong_shape;

    err = client->Infer(&bad, options, {inputs[0]}, outputs);  // missing in1
    CHECK_TRUE(!err.IsOk());

    tc::InferRequestedOutput* bad_out;
    CHECK_OK(tc::InferRequestedOutput::Create(&bad_out, "NO_SUCH_OUTPUT"));
    err = client->Infer(&bad, options, inputs, {bad_out});
    CHECK_TRUE(!err.IsOk());
    delete bad_out;
  }

  // stats accounting
  tc::InferStat stat;
  CHECK_OK(client->ClientInferStat(&stat));
  CHECK_TRUE(stat.completed_request_count >= 2);

  for (auto* i : inputs) delete i;
  delete out0;
  delete out1;
  printf("PASS: http client\n");
}

void TestGrpc(const std::string& url) {
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  CHECK_OK(tc::InferenceServerGrpcClient::Create(&client, url));

  bool live = false, ready = false;
  CHECK_OK(client->IsServerLive(&live));
  CHECK_TRUE(live);
  CHECK_OK(client->IsServerReady(&ready));
  CHECK_TRUE(ready);
  CHECK_OK(client->IsModelReady(&ready, "simple"));
  CHECK_TRUE(ready);

  tc::pb::ServerMetadataResponse server_md;
  CHECK_OK(client->ServerMetadata(&server_md));
  CHECK_TRUE(!server_md.name().empty());
  tc::pb::ModelMetadataResponse model_md;
  CHECK_OK(client->ModelMetadata(&model_md, "simple"));
  CHECK_TRUE(model_md.name() == "simple");
  CHECK_TRUE(model_md.inputs_size() == 2);
  tc::pb::ModelConfigResponse model_cfg;
  CHECK_OK(client->ModelConfig(&model_cfg, "simple"));
  CHECK_TRUE(model_cfg.config().name() == "simple");
  tc::pb::RepositoryIndexResponse index;
  CHECK_OK(client->ModelRepositoryIndex(&index));
  CHECK_TRUE(index.models_size() > 0);

  std::vector<int32_t> input0, input1;
  std::vector<tc::InferInput*> inputs;
  PrepareSimpleInputs(&input0, &input1, &inputs);
  tc::InferRequestedOutput* out0;
  tc::InferRequestedOutput* out1;
  CHECK_OK(tc::InferRequestedOutput::Create(&out0, "OUTPUT0"));
  CHECK_OK(tc::InferRequestedOutput::Create(&out1, "OUTPUT1"));
  std::vector<const tc::InferRequestedOutput*> outputs{out0, out1};

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  CHECK_OK(client->Infer(&result, options, inputs, outputs));
  CheckSimpleResult(result, input0, input1);
  delete result;

  // async
  std::mutex mu;
  std::condition_variable cv;
  tc::InferResult* async_result = nullptr;
  bool done = false;
  CHECK_OK(client->AsyncInfer(
      [&](tc::InferResult* r) {
        std::lock_guard<std::mutex> lk(mu);
        async_result = r;
        done = true;
        cv.notify_one();
      },
      options, inputs, outputs));
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done; });
  }
  CHECK_OK(async_result->RequestStatus());
  CheckSimpleResult(async_result, input0, input1);
  delete async_result;

  // streaming: a 3-step sequence through the stream API
  std::vector<int32_t> seq_outputs;
  CHECK_OK(client->StartStream([&](tc::InferResult* r) {
    tc::Error status = r->RequestStatus();
    if (status.IsOk()) {
      const uint8_t* buf;
      size_t len;
      if (r->RawData("OUTPUT", &buf, &len).IsOk() && len >= 4) {
        int32_t v;
        memcpy(&v, buf, 4);
        seq_outputs.push_back(v);
      }
    }
    delete r;
  }));
  std::vector<int32_t> values{11, 7, 5};
  for (size_t i = 0; i < values.size(); ++i) {
    tc::InferInput* sin;
    CHECK_OK(tc::InferInput::Create(&sin, "INPUT", {1}, "INT32"));
    CHECK_OK(sin->AppendRaw(
        reinterpret_cast<const uint8_t*>(&values[i]), sizeof(int32_t)));
    tc::InferOptions sopt("simple_sequence");
    sopt.sequence_id_ = 777;
    sopt.sequence_start_ = (i == 0);
    sopt.sequence_end_ = (i == values.size() - 1);
    CHECK_OK(client->AsyncStreamInfer(sopt, {sin}));
    delete sin;
  }
  CHECK_OK(client->FinishStream());
  CHECK_TRUE(seq_outputs.size() == 3);
  CHECK_TRUE(seq_outputs[0] == 11 && seq_outputs[1] == 18 &&
             seq_outputs[2] == 23);

  // string (dyna) correlation ids over a second stream
  {
    std::vector<int32_t> dyna_outputs;
    CHECK_OK(client->StartStream([&](tc::InferResult* r) {
      const uint8_t* buf;
      size_t len;
      if (r->RequestStatus().IsOk() &&
          r->RawData("OUTPUT", &buf, &len).IsOk() && len >= 4) {
        int32_t v;
        memcpy(&v, buf, 4);
        dyna_outputs.push_back(v);
      }
      delete r;
    }));
    for (int i = 0; i < 2; ++i) {
      int32_t value = 3;
      tc::InferInput* sin;
      CHECK_OK(tc::InferInput::Create(&sin, "INPUT", {1}, "INT32"));
      CHECK_OK(sin->AppendRaw(
          reinterpret_cast<const uint8_t*>(&value), sizeof(int32_t)));
      tc::InferOptions sopt("simple_dyna_sequence");
      sopt.sequence_id_str_ = "seq-string-id";
      sopt.sequence_start_ = (i == 0);
      sopt.sequence_end_ = (i == 1);
      CHECK_OK(client->AsyncStreamInfer(sopt, {sin}));
      delete sin;
    }
    CHECK_OK(client->FinishStream());
    CHECK_TRUE(dyna_outputs.size() == 2);
    // accumulator seeded with hash(corr id) % 1000, then +3 each step
    CHECK_TRUE(dyna_outputs[1] - dyna_outputs[0] == 3);
  }

  // InferMulti / AsyncInferMulti fan-out
  {
    std::vector<std::vector<tc::InferInput*>> multi_inputs(3, inputs);
    std::vector<tc::InferResult*> results;
    CHECK_OK(client->InferMulti(&results, {options}, multi_inputs));
    CHECK_TRUE(results.size() == 3);
    for (auto* r : results) {
      CheckSimpleResult(r, input0, input1);
      delete r;
    }
    tc::Error multi_err =
        client->InferMulti(&results, {options, options}, multi_inputs);
    CHECK_TRUE(!multi_err.IsOk());

    std::mutex mmu;
    std::condition_variable mcv;
    bool mdone = false;
    std::vector<tc::InferResult*> mresults;
    CHECK_OK(client->AsyncInferMulti(
        [&](std::vector<tc::InferResult*> rs) {
          std::lock_guard<std::mutex> lk(mmu);
          mresults = std::move(rs);
          mdone = true;
          mcv.notify_one();
        },
        {options}, multi_inputs));
    {
      std::unique_lock<std::mutex> lk(mmu);
      mcv.wait(lk, [&] { return mdone; });
    }
    CHECK_TRUE(mresults.size() == 3);
    for (auto* r : mresults) {
      CHECK_OK(r->RequestStatus());
      CheckSimpleResult(r, input0, input1);
      delete r;
    }
  }

  // trace/log settings over gRPC
  {
    tc::pb::TraceSettingResponse trace;
    CHECK_OK(client->GetTraceSettings(&trace));
    CHECK_TRUE(trace.settings().count("trace_level") == 1);
    CHECK_OK(client->UpdateTraceSettings(
        &trace, "", {{"trace_level", {"TIMESTAMPS"}}}));
    CHECK_TRUE(trace.settings().at("trace_level").value(0) == "TIMESTAMPS");
    CHECK_OK(client->UpdateTraceSettings(
        &trace, "", {{"trace_level", {"OFF"}}}));
    tc::pb::LogSettingsResponse log;
    CHECK_OK(client->GetLogSettings(&log));
    CHECK_TRUE(log.settings().count("log_verbose_level") == 1);
    CHECK_OK(client->UpdateLogSettings(&log, {{"log_verbose_level", "1"}}));
    CHECK_OK(client->UpdateLogSettings(&log, {{"log_verbose_level", "0"}}));
  }

  // statistics reflect the traffic this test generated
  {
    tc::pb::ModelStatisticsResponse stats;
    CHECK_OK(client->ModelInferenceStatistics(&stats, "simple"));
    CHECK_TRUE(stats.model_stats_size() == 1);
    CHECK_TRUE(stats.model_stats(0).inference_count() > 0);
  }

  // error matrix
  {
    tc::InferResult* bad = nullptr;
    tc::InferOptions bad_options("no_such_model");
    tc::Error err = client->Infer(&bad, bad_options, inputs, outputs);
    CHECK_TRUE(!err.IsOk());

    tc::InferInput* wrong_dtype;
    CHECK_OK(tc::InferInput::Create(&wrong_dtype, "INPUT0", {1, 16}, "FP32"));
    CHECK_OK(wrong_dtype->AppendRaw(
        reinterpret_cast<const uint8_t*>(input0.data()),
        input0.size() * sizeof(int32_t)));
    err = client->Infer(&bad, options, {wrong_dtype, inputs[1]}, outputs);
    CHECK_TRUE(!err.IsOk());
    delete wrong_dtype;

    err = client->Infer(&bad, options, {inputs[0]}, outputs);
    CHECK_TRUE(!err.IsOk());
  }

  for (auto* i : inputs) delete i;
  delete out0;
  delete out1;
  printf("PASS: grpc client\n");
}

void TestJson() {
  tc_tpu::json::Value doc;
  std::string err;
  CHECK_TRUE(tc_tpu::json::Parse(
      R"({"a": [1, 2.5, "xé", true, null], "b": {"c": -3}})", &doc, &err));
  CHECK_TRUE(doc.At("a").AsArray().size() == 5);
  CHECK_TRUE(doc.At("a").AsArray()[0].AsInt() == 1);
  CHECK_TRUE(doc.At("a").AsArray()[1].AsDouble() == 2.5);
  CHECK_TRUE(doc.At("a").AsArray()[2].AsString() == "x\xc3\xa9");
  CHECK_TRUE(doc.At("b").At("c").AsInt() == -3);
  std::string round = doc.Serialize();
  tc_tpu::json::Value doc2;
  CHECK_TRUE(tc_tpu::json::Parse(round, &doc2, &err));
  CHECK_TRUE(doc2.Serialize() == round);
  CHECK_TRUE(!tc_tpu::json::Parse("{bad", &doc, &err));
  printf("PASS: json\n");
}

}  // namespace

int main(int argc, char** argv) {
  if (argc < 2) {
    fprintf(stderr, "usage: %s <http_host:port> [grpc_host:port]\n",
            argv[0]);
    return 2;
  }
  TestJson();
  TestHttp(argv[1]);
  // real gRPC (h2c) when a gRPC port is given; the grpc-web bridge rides
  // the HTTP port otherwise (the client auto-detects either way)
  TestGrpc(argc > 2 ? argv[2] : argv[1]);
  printf("PASS: all\n");
  return 0;
}
