#include "tls.h"

#include <dlfcn.h>
#include <pthread.h>
#include <signal.h>

#include <cerrno>
#include <cstring>

namespace tc_tpu {
namespace client {

namespace {

// OpenSSL 3 constants (stable ABI values)
constexpr int kSslFiletypePem = 1;
constexpr int kSslFiletypeAsn1 = 2;
constexpr int kSslVerifyNone = 0;
constexpr int kSslVerifyPeer = 1;
constexpr long kSslCtrlSetTlsextHostname = 55;
constexpr long kTlsextNametypeHostName = 0;
constexpr int kSslErrorZeroReturn = 6;
constexpr int kSslErrorSyscall = 5;

struct OpenSsl {
  void* (*tls_client_method)() = nullptr;
  void* (*ctx_new)(void*) = nullptr;
  void (*ctx_free)(void*) = nullptr;
  void* (*ssl_new)(void*) = nullptr;
  void (*ssl_free)(void*) = nullptr;
  int (*set_fd)(void*, int) = nullptr;
  int (*connect)(void*) = nullptr;
  int (*read)(void*, void*, int) = nullptr;
  int (*write)(void*, const void*, int) = nullptr;
  int (*shutdown)(void*) = nullptr;
  int (*get_error)(const void*, int) = nullptr;
  void (*set_verify)(void*, int, void*) = nullptr;
  int (*load_verify)(void*, const char*, const char*) = nullptr;
  int (*default_verify_paths)(void*) = nullptr;
  long (*ssl_ctrl)(void*, int, long, void*) = nullptr;
  int (*set1_host)(void*, const char*) = nullptr;
  int (*use_cert_file)(void*, const char*, int) = nullptr;
  int (*use_key_file)(void*, const char*, int) = nullptr;
  int (*set_alpn)(void*, const unsigned char*, unsigned) = nullptr;
  void (*get_alpn)(const void*, const unsigned char**, unsigned*) = nullptr;
  bool ok = false;

  static const OpenSsl& Get() {
    static OpenSsl s = [] {
      OpenSsl out;
      void* lib = dlopen("libssl.so.3", RTLD_NOW | RTLD_GLOBAL);
      if (lib == nullptr) lib = dlopen("libssl.so", RTLD_NOW | RTLD_GLOBAL);
      if (lib == nullptr) return out;
      auto sym = [lib](const char* n) { return dlsym(lib, n); };
      out.tls_client_method =
          reinterpret_cast<void* (*)()>(sym("TLS_client_method"));
      out.ctx_new = reinterpret_cast<void* (*)(void*)>(sym("SSL_CTX_new"));
      out.ctx_free = reinterpret_cast<void (*)(void*)>(sym("SSL_CTX_free"));
      out.ssl_new = reinterpret_cast<void* (*)(void*)>(sym("SSL_new"));
      out.ssl_free = reinterpret_cast<void (*)(void*)>(sym("SSL_free"));
      out.set_fd = reinterpret_cast<int (*)(void*, int)>(sym("SSL_set_fd"));
      out.connect = reinterpret_cast<int (*)(void*)>(sym("SSL_connect"));
      out.read = reinterpret_cast<int (*)(void*, void*, int)>(sym("SSL_read"));
      out.write = reinterpret_cast<int (*)(void*, const void*, int)>(
          sym("SSL_write"));
      out.shutdown = reinterpret_cast<int (*)(void*)>(sym("SSL_shutdown"));
      out.get_error =
          reinterpret_cast<int (*)(const void*, int)>(sym("SSL_get_error"));
      out.set_verify = reinterpret_cast<void (*)(void*, int, void*)>(
          sym("SSL_CTX_set_verify"));
      out.load_verify = reinterpret_cast<int (*)(void*, const char*,
                                                 const char*)>(
          sym("SSL_CTX_load_verify_locations"));
      out.default_verify_paths = reinterpret_cast<int (*)(void*)>(
          sym("SSL_CTX_set_default_verify_paths"));
      out.ssl_ctrl = reinterpret_cast<long (*)(void*, int, long, void*)>(
          sym("SSL_ctrl"));
      out.set1_host =
          reinterpret_cast<int (*)(void*, const char*)>(sym("SSL_set1_host"));
      out.use_cert_file = reinterpret_cast<int (*)(void*, const char*, int)>(
          sym("SSL_CTX_use_certificate_file"));
      out.use_key_file = reinterpret_cast<int (*)(void*, const char*, int)>(
          sym("SSL_CTX_use_PrivateKey_file"));
      out.set_alpn =
          reinterpret_cast<int (*)(void*, const unsigned char*, unsigned)>(
              sym("SSL_set_alpn_protos"));
      out.get_alpn = reinterpret_cast<void (*)(const void*,
                                               const unsigned char**,
                                               unsigned*)>(
          sym("SSL_get0_alpn_selected"));
      out.ok = out.tls_client_method && out.ctx_new && out.ctx_free &&
               out.ssl_new && out.ssl_free && out.set_fd && out.connect &&
               out.read && out.write && out.shutdown && out.get_error &&
               out.set_verify && out.load_verify &&
               out.default_verify_paths && out.ssl_ctrl && out.set1_host &&
               out.use_cert_file && out.use_key_file && out.set_alpn &&
               out.get_alpn;
      return out;
    }();
    return s;
  }
};

}  // namespace

bool TlsSession::Available() { return OpenSsl::Get().ok; }

TlsContext::~TlsContext() {
  if (ctx_ != nullptr) {
    OpenSsl::Get().ctx_free(ctx_);
    ctx_ = nullptr;
  }
}

Error TlsContext::Init(const HttpSslOptionsView& opts) {
  if (!TlsSession::Available()) {
    return Error("TLS unavailable: libssl.so.3 not found");
  }
  const OpenSsl& o = OpenSsl::Get();
  ctx_ = o.ctx_new(o.tls_client_method());
  if (ctx_ == nullptr) return Error("SSL_CTX_new failed");
  verify_peer_ = opts.verify_peer;
  verify_host_ = opts.verify_host;
  if (opts.verify_peer) {
    o.set_verify(ctx_, kSslVerifyPeer, nullptr);
    int rc = opts.ca_info.empty()
                 ? o.default_verify_paths(ctx_)
                 : o.load_verify(ctx_, opts.ca_info.c_str(), nullptr);
    if (rc != 1) {
      return Error("failed to load CA certificates" +
                   (opts.ca_info.empty() ? std::string()
                                         : " from " + opts.ca_info));
    }
  } else {
    o.set_verify(ctx_, kSslVerifyNone, nullptr);
  }
  if (!opts.cert.empty()) {
    int type = opts.cert_pem ? kSslFiletypePem : kSslFiletypeAsn1;
    if (o.use_cert_file(ctx_, opts.cert.c_str(), type) != 1) {
      return Error("failed to load client certificate " + opts.cert);
    }
  }
  if (!opts.key.empty()) {
    int type = opts.key_pem ? kSslFiletypePem : kSslFiletypeAsn1;
    if (o.use_key_file(ctx_, opts.key.c_str(), type) != 1) {
      return Error("failed to load client key " + opts.key);
    }
  }
  return Error::Success;
}

TlsSession::~TlsSession() { Close(); }

// SSL_write/SSL_shutdown hit write(2) without MSG_NOSIGNAL, so a peer that
// already closed raises SIGPIPE and kills the process (a long-lived
// multiplexed channel makes post-close writes routine, not exotic).  The
// classic library-safe guard: block SIGPIPE on THIS thread around the
// write, consume any pending instance, restore the caller's mask.
class ScopedSigpipeGuard {
 public:
  ScopedSigpipeGuard() {
    sigemptyset(&pipe_set_);
    sigaddset(&pipe_set_, SIGPIPE);
    pthread_sigmask(SIG_BLOCK, &pipe_set_, &old_);
    was_blocked_ = sigismember(&old_, SIGPIPE) == 1;
  }
  ~ScopedSigpipeGuard() {
    if (!was_blocked_) {
      // eat a SIGPIPE our write may have queued, then restore
      struct timespec zero = {0, 0};
      sigtimedwait(&pipe_set_, nullptr, &zero);
      pthread_sigmask(SIG_SETMASK, &old_, nullptr);
    }
  }

 private:
  sigset_t pipe_set_, old_;
  bool was_blocked_ = false;
};

void TlsSession::Close() {
  const OpenSsl& o = OpenSsl::Get();
  std::lock_guard<std::mutex> lk(mu_);
  if (ssl_ != nullptr) {
    ScopedSigpipeGuard guard;
    o.shutdown(ssl_);  // best-effort close_notify
    o.ssl_free(ssl_);
    ssl_ = nullptr;
  }
}

Error TlsSession::Handshake(
    int fd, const TlsContext& ctx, const std::string& host,
    const char* alpn, std::string* alpn_selected) {
  if (!Available()) {
    return Error("TLS unavailable: libssl.so.3 not found");
  }
  if (!ctx.initialized()) {
    return Error("TLS context not initialized");
  }
  const OpenSsl& o = OpenSsl::Get();
  std::lock_guard<std::mutex> lk(mu_);
  ssl_ = o.ssl_new(ctx.ctx_);
  if (ssl_ == nullptr) {
    return Error("SSL_new failed");
  }
  // SNI + hostname verification
  o.ssl_ctrl(ssl_, kSslCtrlSetTlsextHostname, kTlsextNametypeHostName,
             const_cast<char*>(host.c_str()));
  if (alpn != nullptr) {
    // wire format: length-prefixed protocol list
    std::string wire;
    wire.push_back(static_cast<char>(strlen(alpn)));
    wire.append(alpn);
    o.set_alpn(ssl_, reinterpret_cast<const unsigned char*>(wire.data()),
               static_cast<unsigned>(wire.size()));
  }
  if (ctx.verify_peer_ && ctx.verify_host_) {
    o.set1_host(ssl_, host.c_str());
  }
  if (o.set_fd(ssl_, fd) != 1) {
    o.ssl_free(ssl_);
    ssl_ = nullptr;
    return Error("SSL_set_fd failed");
  }
  ScopedSigpipeGuard guard;
  int rc = o.connect(ssl_);
  if (rc != 1) {
    int err = o.get_error(ssl_, rc);
    o.ssl_free(ssl_);
    ssl_ = nullptr;
    return Error(
        "TLS handshake with " + host + " failed (ssl error " +
        std::to_string(err) +
        (err == 1 ? ": certificate verification failed or protocol error"
                  : "") +
        ")");
  }
  if (alpn_selected != nullptr) {
    const unsigned char* sel = nullptr;
    unsigned sel_len = 0;
    o.get_alpn(ssl_, &sel, &sel_len);
    if (sel != nullptr && sel_len > 0) {
      alpn_selected->assign(reinterpret_cast<const char*>(sel), sel_len);
    } else {
      alpn_selected->clear();
    }
  }
  return Error::Success;
}

long TlsSession::Recv(char* buf, size_t n) {
  const OpenSsl& o = OpenSsl::Get();
  std::lock_guard<std::mutex> lk(mu_);
  if (ssl_ == nullptr) {
    errno = EBADF;
    return -1;
  }
  // SSL_read can itself WRITE (close_notify reply, key update) — same
  // SIGPIPE exposure as Send when the peer is already gone
  ScopedSigpipeGuard guard;
  int rc = o.read(ssl_, buf, static_cast<int>(n));
  if (rc > 0) return rc;
  int err = o.get_error(ssl_, rc);
  if (err == kSslErrorZeroReturn) return 0;  // clean TLS close
  if (err == kSslErrorSyscall && rc == 0) return 0;  // peer FIN
  // errno (EAGAIN on SO_RCVTIMEO expiry) is preserved for the caller
  return -1;
}

long TlsSession::Send(const char* buf, size_t n) {
  const OpenSsl& o = OpenSsl::Get();
  std::lock_guard<std::mutex> lk(mu_);
  if (ssl_ == nullptr) {
    errno = EBADF;
    return -1;
  }
  ScopedSigpipeGuard guard;
  int rc = o.write(ssl_, buf, static_cast<int>(n));
  if (rc > 0) return rc;
  return -1;
}

}  // namespace client
}  // namespace tc_tpu
