// TLS client stream over an already-connected socket.
//
// Parity target: the reference C++ clients' HttpSslOptions /
// libcurl CURLOPT_SSL_* handling (/root/reference/src/c++/library/
// http_client.cc SetSSLCurlOptions) and grpc SslCredentials.  The image
// ships no OpenSSL/GnuTLS headers, so the needed OpenSSL 3 API subset
// (opaque pointers + stable C ABI) is declared here and resolved from the
// system libssl.so.3 at runtime via dlopen — when the library is missing,
// Available() is false and secure clients fail with a clear error.
#pragma once

#include <cstddef>
#include <mutex>
#include <string>

#include "common.h"

namespace tc_tpu {
namespace client {

struct HttpSslOptionsView {
  bool verify_peer = true;
  bool verify_host = true;
  std::string ca_info;      // PEM CA bundle path ("" = system default)
  std::string cert;         // client cert path (PEM/DER per cert_pem)
  bool cert_pem = true;
  std::string key;          // client key path
  bool key_pem = true;
};

// Shared per-transport TLS configuration: one SSL_CTX built (and the CA
// bundle / client cert files validated) ONCE at EnableTls time, not per
// connection.
class TlsContext {
 public:
  TlsContext() = default;
  ~TlsContext();
  TlsContext(const TlsContext&) = delete;
  TlsContext& operator=(const TlsContext&) = delete;

  Error Init(const HttpSslOptionsView& opts);
  bool initialized() const { return ctx_ != nullptr; }

 private:
  friend class TlsSession;
  void* ctx_ = nullptr;   // SSL_CTX*
  bool verify_host_ = true;
  bool verify_peer_ = true;
};

class TlsSession {
 public:
  // True when libssl.so.3 (or libssl.so) is loadable.
  static bool Available();

  TlsSession() = default;
  ~TlsSession();
  TlsSession(const TlsSession&) = delete;
  TlsSession& operator=(const TlsSession&) = delete;

  // TLS handshake over `fd` (blocking; honors SO_RCVTIMEO/SO_SNDTIMEO the
  // caller may have set).  `host` is used for SNI and (when the context
  // verifies hosts) hostname verification.  `alpn` (e.g. "h2") offers that
  // protocol; `alpn_selected` receives what the server negotiated ("" when
  // the server picked nothing — callers decide whether to proceed).
  Error Handshake(int fd, const TlsContext& ctx, const std::string& host,
                  const char* alpn = nullptr,
                  std::string* alpn_selected = nullptr);

  // Like ::recv/::send on the cleartext stream: >0 bytes, 0 orderly close,
  // -1 error (errno EAGAIN/EWOULDBLOCK preserved for deadline handling).
  // Internally serialized: OpenSSL SSL objects are not thread-safe even
  // for concurrent read-vs-write (the duplex stream's reader thread and
  // writer thread share one session), so both calls take the session
  // mutex — duplex readers use a short SO_RCVTIMEO so a blocked Recv
  // releases the lock periodically for writers.
  long Recv(char* buf, size_t n);
  long Send(const char* buf, size_t n);

  void Close();  // best-effort SSL_shutdown + free

 private:
  void* ssl_ = nullptr;   // SSL*
  std::mutex mu_;
};

}  // namespace client
}  // namespace tc_tpu
