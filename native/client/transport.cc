#include "transport.h"

#include "connio.h"
#include "sockio.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <chrono>

#include <algorithm>
#include <cerrno>
#include <cstdio>
#include <cstring>
#include <sstream>

namespace tc_tpu {
namespace client {

namespace {

std::string LowerCopy(const std::string& s) {
  std::string out = s;
  std::transform(out.begin(), out.end(), out.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return out;
}

using sockio::ConnectTcp;
using sockio::Deadline;
using sockio::ReadExactDl;
using sockio::RecvDl;
using sockio::SetSocketTimeout;
using sockio::WriteAll;
using sockio::WriteAllDl;

using connio::CReadExactDl;
using connio::CRecvDl;
using connio::CWriteAll;
using connio::CWriteAllDl;
using connio::ConnRef;

}  // namespace

std::string Base64Encode(const uint8_t* data, size_t len) {
  static const char tbl[] =
      "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789+/";
  std::string out;
  out.reserve(((len + 2) / 3) * 4);
  for (size_t i = 0; i < len; i += 3) {
    uint32_t n = data[i] << 16;
    if (i + 1 < len) n |= data[i + 1] << 8;
    if (i + 2 < len) n |= data[i + 2];
    out += tbl[(n >> 18) & 63];
    out += tbl[(n >> 12) & 63];
    out += (i + 1 < len) ? tbl[(n >> 6) & 63] : '=';
    out += (i + 2 < len) ? tbl[n & 63] : '=';
  }
  return out;
}

HttpTransport::HttpTransport(std::string host, int port, size_t max_idle_conns)
    : host_(std::move(host)), port_(port), max_idle_(max_idle_conns) {}

void HttpTransport::SetTcpKeepAlive(int idle_s, int intvl_s) {
  keepalive_idle_s_ = idle_s > 0 ? idle_s : 0;
  keepalive_intvl_s_ = intvl_s > 0 ? intvl_s : 0;
}

void HttpTransport::SetMaxResponseBytes(size_t max_bytes) {
  max_response_bytes_ = max_bytes;
}

Error HttpTransport::EnableTls(const HttpSslOptionsView& opts) {
  if (!TlsSession::Available()) {
    return Error(
        "TLS unavailable: system libssl.so.3 not found (required for "
        "use_ssl)");
  }
  TC_RETURN_IF_ERROR(tls_ctx_.Init(opts));
  use_tls_ = true;
  return Error::Success;
}

void HttpTransport::SetMaxRequestBytes(size_t max_bytes) {
  max_request_bytes_ = max_bytes;
}

HttpTransport::~HttpTransport() {
  std::lock_guard<std::mutex> lk(mu_);
  for (auto& c : idle_) {
    delete c.tls;
    ::close(c.fd);
  }
  idle_.clear();
}

void HttpTransport::Release(Conn conn, bool reusable) {
  if (reusable) {
    std::lock_guard<std::mutex> lk(mu_);
    if (idle_.size() < max_idle_) {
      idle_.push_back(conn);
      return;
    }
  }
  delete conn.tls;  // TlsSession dtor sends close_notify
  if (conn.fd >= 0) ::close(conn.fd);
}

Error HttpTransport::Request(
    const std::string& method, const std::string& path,
    const std::string& body, const Headers& extra_headers, Response* out,
    RequestTimers* timers, uint64_t timeout_us) {
  if (max_request_bytes_ > 0 && body.size() > max_request_bytes_) {
    return Error(
        "request exceeds maximum send message size of " +
        std::to_string(max_request_bytes_) + " bytes");
  }
  Deadline dl = Deadline::In(timeout_us);
  Error err;
  Conn pooled{-1, nullptr};
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (!idle_.empty()) {
      pooled = idle_.back();
      idle_.pop_back();
    }
  }
  if (pooled.fd < 0) {
    pooled.fd = ConnectTcp(host_, port_, &err, dl);
    if (pooled.fd < 0) return err;
    if (keepalive_idle_s_ > 0) {
      int one = 1;
      ::setsockopt(pooled.fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
      ::setsockopt(pooled.fd, IPPROTO_TCP, TCP_KEEPIDLE, &keepalive_idle_s_,
                   sizeof(keepalive_idle_s_));
      if (keepalive_intvl_s_ > 0)
        ::setsockopt(pooled.fd, IPPROTO_TCP, TCP_KEEPINTVL,
                     &keepalive_intvl_s_, sizeof(keepalive_intvl_s_));
    }
    if (use_tls_) {
      if (dl.enabled) {
        long long rem = dl.RemainingUs();
        if (rem <= 0) {
          ::close(pooled.fd);
          return Error("Deadline Exceeded: timed out before TLS handshake");
        }
        SetSocketTimeout(pooled.fd, SO_RCVTIMEO, rem);
        SetSocketTimeout(pooled.fd, SO_SNDTIMEO, rem);
      }
      pooled.tls = new TlsSession();
      Error terr = pooled.tls->Handshake(pooled.fd, tls_ctx_, host_);
      if (!terr.IsOk()) {
        delete pooled.tls;
        ::close(pooled.fd);
        return terr;
      }
    }
  }
  const ConnRef conn{pooled.fd, pooled.tls};

  std::ostringstream req;
  req << method << " /" << path << " HTTP/1.1\r\n";
  req << "Host: " << host_ << ":" << port_ << "\r\n";
  req << "Connection: keep-alive\r\n";
  req << "Content-Length: " << body.size() << "\r\n";
  bool has_ct = false;
  for (const auto& kv : extra_headers) {
    if (LowerCopy(kv.first) == "content-type") has_ct = true;
    req << kv.first << ": " << kv.second << "\r\n";
  }
  if (!has_ct && method == "POST") {
    req << "Content-Type: application/octet-stream\r\n";
  }
  req << "\r\n";
  std::string head = req.str();

  if (timers) timers->CaptureTimestamp(RequestTimers::Kind::SEND_START);
  int wrc = CWriteAllDl(conn, head.data(), head.size(), dl);
  if (wrc == 0 && !body.empty()) {
    wrc = CWriteAllDl(conn, body.data(), body.size(), dl);
  }
  if (timers) timers->CaptureTimestamp(RequestTimers::Kind::SEND_END);
  if (wrc != 0) {
    Release(pooled, false);
    return Error(
        wrc == -2 ? "Deadline Exceeded: timed out sending request to " + host_
                  : "failed to send request to " + host_);
  }

  if (timers) timers->CaptureTimestamp(RequestTimers::Kind::RECV_START);
  std::string buf;
  buf.reserve(8192);
  char chunk[8192];
  size_t header_end = std::string::npos;
  while (header_end == std::string::npos) {
    ssize_t r = CRecvDl(conn, chunk, sizeof(chunk), dl);
    if (r <= 0) {
      Release(pooled, false);
      return Error(
          r == -2 ? "Deadline Exceeded: timed out awaiting response"
                  : "connection closed while reading response headers");
    }
    buf.append(chunk, static_cast<size_t>(r));
    header_end = buf.find("\r\n\r\n");
    if (buf.size() > (1u << 20)) {
      Release(pooled, false);
      return Error("response headers too large");
    }
  }

  std::string head_str = buf.substr(0, header_end);
  std::string rest = buf.substr(header_end + 4);
  std::istringstream hs(head_str);
  std::string status_line;
  std::getline(hs, status_line);
  if (!status_line.empty() && status_line.back() == '\r') status_line.pop_back();
  int status = 0;
  {
    auto sp = status_line.find(' ');
    if (sp != std::string::npos) status = atoi(status_line.c_str() + sp + 1);
  }
  Headers resp_headers;
  std::string line;
  while (std::getline(hs, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = LowerCopy(line.substr(0, colon));
    size_t vstart = colon + 1;
    while (vstart < line.size() && line[vstart] == ' ') ++vstart;
    resp_headers[key] = line.substr(vstart);
  }

  std::string resp_body;
  bool keep_alive = true;
  auto over_cap = [this](size_t sz) {
    return max_response_bytes_ > 0 && sz > max_response_bytes_;
  };
  auto cap_error = [this, &pooled]() {
    Release(pooled, false);
    return Error(
        "response exceeds maximum receive message size of " +
        std::to_string(max_response_bytes_) + " bytes");
  };
  auto te = resp_headers.find("transfer-encoding");
  if (te != resp_headers.end() &&
      LowerCopy(te->second).find("chunked") != std::string::npos) {
    std::string stream = std::move(rest);
    size_t pos = 0;
    while (true) {
      size_t nl = stream.find("\r\n", pos);
      while (nl == std::string::npos) {
        ssize_t r = CRecvDl(conn, chunk, sizeof(chunk), dl);
        if (r <= 0) {
          Release(pooled, false);
          return Error(r == -2 ? "Deadline Exceeded: timed out mid-chunk"
                               : "connection closed mid-chunk");
        }
        stream.append(chunk, static_cast<size_t>(r));
        nl = stream.find("\r\n", pos);
      }
      size_t chunk_len =
          strtoul(stream.substr(pos, nl - pos).c_str(), nullptr, 16);
      // enforce the cap on the DECLARED size before buffering the chunk —
      // one huge chunk must not be allocated just to be rejected
      if (over_cap(resp_body.size() + chunk_len)) return cap_error();
      size_t data_start = nl + 2;
      while (stream.size() < data_start + chunk_len + 2) {
        ssize_t r = CRecvDl(conn, chunk, sizeof(chunk), dl);
        if (r <= 0) {
          Release(pooled, false);
          return Error(r == -2 ? "Deadline Exceeded: timed out mid-chunk"
                               : "connection closed mid-chunk");
        }
        stream.append(chunk, static_cast<size_t>(r));
      }
      if (chunk_len == 0) break;
      resp_body.append(stream, data_start, chunk_len);
      if (over_cap(resp_body.size())) return cap_error();
      pos = data_start + chunk_len + 2;
    }
  } else {
    auto cl = resp_headers.find("content-length");
    resp_body = std::move(rest);
    if (cl != resp_headers.end()) {
      size_t want = strtoul(cl->second.c_str(), nullptr, 10);
      if (over_cap(want)) return cap_error();
      if (resp_body.size() < want) {
        size_t missing = want - resp_body.size();
        size_t old = resp_body.size();
        resp_body.resize(want);
        int rrc = CReadExactDl(conn, &resp_body[old], missing, dl);
        if (rrc != 0) {
          Release(pooled, false);
          return Error(
              rrc == -2 ? "Deadline Exceeded: timed out reading response body"
                        : "connection closed while reading response body");
        }
      } else if (resp_body.size() > want) {
        resp_body.resize(want);
      }
    } else if (status == 204 || status == 304 || status < 200) {
      // These statuses never carry a body (HTTP/1.1 §3.3.3) — absent
      // framing headers do not make them close-delimited.
      resp_body.clear();
    } else {
      // Close-delimited body (HTTP/1.1 §3.3.3): no framing header means
      // the body runs until the peer cleanly closes the connection.  Only
      // an orderly FIN (r == 0) terminates the body; a socket error means
      // the response was truncated.
      if (over_cap(resp_body.size())) return cap_error();
      for (;;) {
        ssize_t r = CRecvDl(conn, chunk, sizeof(chunk), dl);
        if (r == 0) break;
        if (r < 0) {
          Release(pooled, false);
          return Error(
              r == -2 ? "Deadline Exceeded: timed out reading response body"
                      : "connection error while reading response body");
        }
        resp_body.append(chunk, static_cast<size_t>(r));
        if (over_cap(resp_body.size())) return cap_error();
      }
      keep_alive = false;
    }
  }
  if (timers) timers->CaptureTimestamp(RequestTimers::Kind::RECV_END);

  auto conn_hdr = resp_headers.find("connection");
  if (conn_hdr != resp_headers.end() &&
      LowerCopy(conn_hdr->second) == "close") {
    keep_alive = false;
  }
  if (dl.enabled && keep_alive) {
    // pooled fds must not inherit this request's deadline
    SetSocketTimeout(pooled.fd, SO_RCVTIMEO, 0);
    SetSocketTimeout(pooled.fd, SO_SNDTIMEO, 0);
  }
  Release(pooled, keep_alive);

  out->status = status;
  out->headers = std::move(resp_headers);
  out->body = std::move(resp_body);
  return Error::Success;
}

//==============================================================================
DuplexConnection::~DuplexConnection() { Close(); }

void DuplexConnection::Close() {
  if (tls_ != nullptr) {
    delete tls_;  // dtor sends close_notify
    tls_ = nullptr;
  }
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
}

Error DuplexConnection::Open(
    const std::string& host, int port, const std::string& path,
    const Headers& extra_headers, int keepalive_idle_s,
    int keepalive_intvl_s, const TlsContext* tls_ctx) {
  Error err;
  fd_ = ConnectTcp(host, port, &err);
  if (fd_ < 0) return err;
  if (tls_ctx != nullptr) {
    tls_ = new TlsSession();
    Error terr = tls_->Handshake(fd_, *tls_ctx, host);
    if (!terr.IsOk()) {
      Close();
      return terr;
    }
    // short receive timeout: the stream reader must release the SSL
    // session mutex periodically so concurrent writers (one SSL object is
    // never safe for simultaneous read+write) get their turn
    SetSocketTimeout(fd_, SO_RCVTIMEO, 50000);
  }
  if (keepalive_idle_s > 0) {
    int one = 1;
    ::setsockopt(fd_, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
    ::setsockopt(fd_, IPPROTO_TCP, TCP_KEEPIDLE, &keepalive_idle_s,
                 sizeof(keepalive_idle_s));
    if (keepalive_intvl_s > 0)
      ::setsockopt(fd_, IPPROTO_TCP, TCP_KEEPINTVL, &keepalive_intvl_s,
                   sizeof(keepalive_intvl_s));
  }

  std::ostringstream req;
  req << "POST /" << path << " HTTP/1.1\r\n";
  req << "Host: " << host << ":" << port << "\r\n";
  req << "Connection: close\r\n";
  req << "Transfer-Encoding: chunked\r\n";
  req << "TE: trailers\r\n";
  bool has_ct = false;
  for (const auto& kv : extra_headers) {
    if (LowerCopy(kv.first) == "content-type") has_ct = true;
    req << kv.first << ": " << kv.second << "\r\n";
  }
  if (!has_ct) req << "Content-Type: application/grpc-web+proto\r\n";
  req << "\r\n";
  std::string head = req.str();
  if (!CWriteAll(ConnRef{fd_, tls_}, head.data(), head.size())) {
    Close();
    return Error("failed to send stream request headers");
  }
  return Error::Success;
}

Error DuplexConnection::WriteChunk(const std::string& data) {
  if (fd_ < 0) return Error("stream connection is closed");
  if (data.empty()) return Error::Success;
  char size_line[32];
  int n = snprintf(size_line, sizeof(size_line), "%zx\r\n", data.size());
  std::string wire;
  wire.reserve(n + data.size() + 2);
  wire.append(size_line, n);
  wire.append(data);
  wire.append("\r\n");
  if (!CWriteAll(ConnRef{fd_, tls_}, wire.data(), wire.size())) {
    return Error("failed to send stream request chunk");
  }
  return Error::Success;
}

Error DuplexConnection::WriteEnd() {
  if (fd_ < 0) return Error("stream connection is closed");
  static const char kEnd[] = "0\r\n\r\n";
  if (!CWriteAll(ConnRef{fd_, tls_}, kEnd, sizeof(kEnd) - 1)) {
    return Error("failed to finish stream request body");
  }
  return Error::Success;
}

Error DuplexConnection::Fill(bool* eof) {
  if (eof) *eof = false;
  char chunk[8192];
  ssize_t r;
  for (;;) {
    r = tls_ != nullptr
            ? static_cast<ssize_t>(tls_->Recv(chunk, sizeof(chunk)))
            : ::recv(fd_, chunk, sizeof(chunk), 0);
    if (r < 0 && tls_ != nullptr &&
        (errno == EAGAIN || errno == EWOULDBLOCK)) {
      continue;  // SO_RCVTIMEO tick: lock released for writers; retry
    }
    break;
  }
  if (r < 0) return Error("connection error while reading stream response");
  if (r == 0) {
    if (eof) {
      *eof = true;
      return Error::Success;
    }
    return Error("connection closed mid stream response");
  }
  rbuf_.append(chunk, static_cast<size_t>(r));
  return Error::Success;
}

Error DuplexConnection::ReadResponseHeaders(int* status, Headers* headers) {
  if (fd_ < 0) return Error("stream connection is closed");
  size_t header_end;
  while ((header_end = rbuf_.find("\r\n\r\n")) == std::string::npos) {
    TC_RETURN_IF_ERROR(Fill());
    if (rbuf_.size() > (1u << 20)) return Error("response headers too large");
  }
  std::istringstream hs(rbuf_.substr(0, header_end));
  rbuf_.erase(0, header_end + 4);
  std::string status_line;
  std::getline(hs, status_line);
  if (!status_line.empty() && status_line.back() == '\r') status_line.pop_back();
  *status = 0;
  {
    auto sp = status_line.find(' ');
    if (sp != std::string::npos) *status = atoi(status_line.c_str() + sp + 1);
  }
  std::string line;
  while (std::getline(hs, line)) {
    if (!line.empty() && line.back() == '\r') line.pop_back();
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = LowerCopy(line.substr(0, colon));
    size_t vstart = colon + 1;
    while (vstart < line.size() && line[vstart] == ' ') ++vstart;
    (*headers)[key] = line.substr(vstart);
  }
  auto te = headers->find("transfer-encoding");
  chunked_ = te != headers->end() &&
             LowerCopy(te->second).find("chunked") != std::string::npos;
  if (!chunked_) {
    auto cl = headers->find("content-length");
    remaining_ =
        cl != headers->end() ? strtoll(cl->second.c_str(), nullptr, 10) : -1;
    if (remaining_ == 0) body_done_ = true;
  } else {
    remaining_ = 0;  // at a chunk boundary
  }
  headers_read_ = true;
  return Error::Success;
}

Error DuplexConnection::ReadSome(std::string* out, bool* done) {
  *done = false;
  if (!headers_read_) return Error("response headers not read yet");
  if (body_done_) {
    *done = true;
    return Error::Success;
  }
  if (!chunked_) {
    // content-length (remaining_ >= 0) or close-delimited (remaining_ < 0)
    if (rbuf_.empty()) {
      if (remaining_ < 0) {
        bool eof = false;
        TC_RETURN_IF_ERROR(Fill(&eof));
        if (eof) {
          body_done_ = true;
          *done = true;
          return Error::Success;
        }
      } else {
        TC_RETURN_IF_ERROR(Fill());
      }
    }
    size_t take = rbuf_.size();
    if (remaining_ >= 0) {
      take = std::min<long long>(take, remaining_);
      remaining_ -= take;
      if (remaining_ == 0) body_done_ = true;
    }
    out->append(rbuf_, 0, take);
    rbuf_.erase(0, take);
    *done = body_done_;
    return Error::Success;
  }
  // chunked: decode whatever complete pieces are buffered; block only when
  // nothing was produced yet
  for (;;) {
    bool produced = false;
    for (;;) {
      if (remaining_ > 0) {
        size_t take = std::min<long long>(rbuf_.size(), remaining_);
        if (take == 0) break;
        out->append(rbuf_, 0, take);
        rbuf_.erase(0, take);
        remaining_ -= take;
        produced = true;
        if (remaining_ > 0) break;  // need more of this chunk
        remaining_ = -2;            // expect CRLF after chunk data
      }
      if (remaining_ == -2) {
        if (rbuf_.size() < 2) break;
        rbuf_.erase(0, 2);
        remaining_ = 0;
      }
      // at a chunk-size line
      size_t nl = rbuf_.find("\r\n");
      if (nl == std::string::npos) break;
      long long len = strtoll(rbuf_.substr(0, nl).c_str(), nullptr, 16);
      rbuf_.erase(0, nl + 2);
      if (len == 0) {
        // terminal chunk; consume optional trailers until blank line
        body_done_ = true;
        *done = true;
        return Error::Success;
      }
      remaining_ = len;
    }
    if (produced) {
      *done = body_done_;
      return Error::Success;
    }
    TC_RETURN_IF_ERROR(Fill());
  }
}

}  // namespace client
}  // namespace tc_tpu
