// KV-cache incremental generation from C++ over the live duplex stream
// (framework extension mirrored from examples/simple_grpc_decode_client.py):
// send the 128-token prompt window ONCE with sequence_start, then feed each
// returned NEXT_TOKEN back as a single-token step — no window recompute.

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

static constexpr int kWindow = 128;  // llama_decode prompt window

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int n_tokens = 5;
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "-n") == 0) n_tokens = atoi(argv[i + 1]);
  }

  // declared BEFORE the client: the stream callback captures these, and the
  // client's destructor joins its reader thread — reverse destruction order
  // must tear the client down first
  std::mutex mu;
  std::condition_variable cv;
  std::queue<int32_t> tokens_q;
  bool stream_error = false;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  err = client->StartStream([&](tc::InferResult* r) {
    const uint8_t* buf;
    size_t len;
    if (r->RequestStatus().IsOk() &&
        r->RawData("NEXT_TOKEN", &buf, &len).IsOk() && len >= 4) {
      int32_t tok;
      memcpy(&tok, buf, 4);
      std::lock_guard<std::mutex> lk(mu);
      tokens_q.push(tok);
      cv.notify_all();
    } else {
      fprintf(stderr, "stream result error: %s\n",
              r->RequestStatus().IsOk()
                  ? "response missing a valid NEXT_TOKEN tensor"
                  : r->RequestStatus().Message().c_str());
      std::lock_guard<std::mutex> lk(mu);
      stream_error = true;
      cv.notify_all();
    }
    delete r;
  });
  if (!err.IsOk()) {
    fprintf(stderr, "start stream failed: %s\n", err.Message().c_str());
    return 1;
  }

  // left-padded byte-level prompt window, as llama_preprocess builds it
  const std::string prompt = "In a hole in the ground";
  std::vector<int32_t> window(kWindow, 0);
  for (size_t i = 0; i < prompt.size(); ++i)
    window[kWindow - prompt.size() + i] =
        static_cast<int32_t>(static_cast<unsigned char>(prompt[i]));

  auto send = [&](const std::vector<int32_t>& vals, bool start, bool end) {
    tc::InferInput* in;
    tc::InferInput::Create(&in, "TOKENS",
                           {static_cast<int64_t>(vals.size())}, "INT32");
    in->AppendRaw(reinterpret_cast<const uint8_t*>(vals.data()),
                  vals.size() * sizeof(int32_t));
    tc::InferOptions options("llama_decode");
    options.sequence_id_ = 8101;
    options.sequence_start_ = start;
    options.sequence_end_ = end;
    tc::Error e = client->AsyncStreamInfer(options, {in});
    delete in;
    return e;
  };

  auto next_token = [&](int32_t* tok) {
    std::unique_lock<std::mutex> lk(mu);
    if (!cv.wait_for(lk, std::chrono::seconds(600), [&] {
          return !tokens_q.empty() || stream_error;
        }))
      return false;
    if (stream_error || tokens_q.empty()) return false;
    *tok = tokens_q.front();
    tokens_q.pop();
    return true;
  };

  err = send(window, /*start=*/true, /*end=*/false);
  if (!err.IsOk()) {
    fprintf(stderr, "prefill failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<int32_t> produced;
  int32_t tok = 0;
  for (int step = 0; step < n_tokens; ++step) {
    if (!next_token(&tok)) {
      fprintf(stderr, "no token for step %d\n", step);
      return 1;
    }
    produced.push_back(tok);
    err = send({tok}, /*start=*/false, /*end=*/step == n_tokens - 1);
    if (!err.IsOk()) {
      fprintf(stderr, "step failed: %s\n", err.Message().c_str());
      return 1;
    }
  }
  if (!next_token(&tok)) {
    fprintf(stderr, "missing final token\n");
    return 1;
  }
  client->FinishStream();

  printf("generated:");
  for (int32_t t : produced) printf(" %d", t);
  printf("\nPASS: grpc decode (kv cache)\n");
  return 0;
}
