// Model repository control over HTTP (reference
// src/c++/examples/simple_http_model_control.cc behavior).

#include <cstdio>
#include <cstring>
#include <string>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  const std::string model = "identity_fp32";
  bool ready = false;
  if (!client->UnloadModel(model).IsOk()) {
    fprintf(stderr, "unload failed\n");
    return 1;
  }
  if (!client->IsModelReady(&ready, model).IsOk()) {
    fprintf(stderr, "IsModelReady RPC failed\n");
    return 1;
  }
  if (ready) {
    fprintf(stderr, "model still ready after unload\n");
    return 1;
  }
  if (!client->LoadModel(model).IsOk()) {
    fprintf(stderr, "load failed\n");
    return 1;
  }
  if (!client->IsModelReady(&ready, model).IsOk()) {
    fprintf(stderr, "IsModelReady RPC failed\n");
    return 1;
  }
  if (!ready) {
    fprintf(stderr, "model not ready after load\n");
    return 1;
  }
  // loading an unknown model must fail
  if (client->LoadModel("wrong_model_name").IsOk()) {
    fprintf(stderr, "expected error loading unknown model\n");
    return 1;
  }
  printf("PASS: http model control\n");
  return 0;
}
