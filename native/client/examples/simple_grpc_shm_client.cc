// System shared-memory data path over gRPC (reference
// src/c++/examples/simple_grpc_shm_client.cc behavior): create/map POSIX
// shm, register, infer with shm inputs+outputs, read results from the
// region, unregister/unlink.

#include <fcntl.h>
#include <string.h>
#include <sys/mman.h>
#include <unistd.h>

#include <cstdio>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  const char* in_key = "/cc_grpc_input_shm";
  const char* out_key = "/cc_grpc_output_shm";
  const size_t in_bytes = 2 * 16 * sizeof(int32_t);
  const size_t out_bytes = 2 * 16 * sizeof(int32_t);
  shm_unlink(in_key);
  shm_unlink(out_key);
  int in_fd = shm_open(in_key, O_RDWR | O_CREAT, 0600);
  int out_fd = shm_open(out_key, O_RDWR | O_CREAT, 0600);
  if (in_fd < 0 || out_fd < 0 || ftruncate(in_fd, in_bytes) != 0 ||
      ftruncate(out_fd, out_bytes) != 0) {
    fprintf(stderr, "shm setup failed\n");
    return 1;
  }
  int32_t* in_base = static_cast<int32_t*>(mmap(
      nullptr, in_bytes, PROT_READ | PROT_WRITE, MAP_SHARED, in_fd, 0));
  int32_t* out_base = static_cast<int32_t*>(mmap(
      nullptr, out_bytes, PROT_READ | PROT_WRITE, MAP_SHARED, out_fd, 0));
  if (in_base == MAP_FAILED || out_base == MAP_FAILED) {
    fprintf(stderr, "mmap failed\n");
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    in_base[i] = i;       // INPUT0 at offset 0
    in_base[16 + i] = 1;  // INPUT1 at offset 64
  }
  if (!client->RegisterSystemSharedMemory("grpc_in", in_key, in_bytes)
           .IsOk() ||
      !client->RegisterSystemSharedMemory("grpc_out", out_key, out_bytes)
           .IsOk()) {
    fprintf(stderr, "register failed\n");
    return 1;
  }
  tc::InferInput *in0, *in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  in0->SetSharedMemory("grpc_in", 16 * sizeof(int32_t), 0);
  in1->SetSharedMemory("grpc_in", 16 * sizeof(int32_t), 16 * sizeof(int32_t));
  tc::InferRequestedOutput *o0, *o1;
  tc::InferRequestedOutput::Create(&o0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&o1, "OUTPUT1");
  o0->SetSharedMemory("grpc_out", 16 * sizeof(int32_t), 0);
  o1->SetSharedMemory("grpc_out", 16 * sizeof(int32_t), 16 * sizeof(int32_t));
  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {in0, in1}, {o0, o1});
  if (!err.IsOk()) {
    fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    if (out_base[i] != i + 1 || out_base[16 + i] != i - 1) {
      fprintf(stderr, "shm output mismatch at %d\n", i);
      return 1;
    }
  }
  delete result;
  delete in0;
  delete in1;
  delete o0;
  delete o1;
  client->UnregisterSystemSharedMemory("grpc_in");
  client->UnregisterSystemSharedMemory("grpc_out");
  munmap(in_base, in_bytes);
  munmap(out_base, out_bytes);
  close(in_fd);
  close(out_fd);
  shm_unlink(in_key);
  shm_unlink(out_key);
  printf("PASS: grpc system shm\n");
  return 0;
}
