// BYTES tensor round trip over HTTP (reference
// src/c++/examples/simple_http_string_infer_client.cc behavior).

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<std::string> values{"alpha", "βeta", "", "delta"};
  tc::InferInput* input;
  tc::InferInput::Create(&input, "INPUT0", {1, 4}, "BYTES");
  input->AppendFromString(values);
  tc::InferOptions options("simple_identity");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {input});
  if (!err.IsOk()) {
    fprintf(stderr, "inference failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<std::string> echoed;
  err = result->StringData("OUTPUT0", &echoed);
  if (!err.IsOk() || echoed != values) {
    fprintf(stderr, "string round trip mismatch\n");
    return 1;
  }
  delete result;
  delete input;
  printf("PASS: http string infer\n");
  return 0;
}
