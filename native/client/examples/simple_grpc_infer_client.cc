// C++ gRPC example (reference src/c++/examples/simple_grpc_infer_client.cc
// behavior) — rides the gRPC-Web bridge on the HTTP port.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }

  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 2;
  }
  tc::InferInput* in0;
  tc::InferInput* in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  in0->AppendRaw(reinterpret_cast<const uint8_t*>(input0.data()),
                 input0.size() * sizeof(int32_t));
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(input1.data()),
                 input1.size() * sizeof(int32_t));

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {in0, in1});
  if (!err.IsOk()) {
    fprintf(stderr, "inference failed: %s\n", err.Message().c_str());
    return 1;
  }
  const uint8_t* buf;
  size_t len;
  result->RawData("OUTPUT0", &buf, &len);
  const int32_t* sum = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sum[i] != input0[i] + input1[i]) {
      fprintf(stderr, "sum mismatch at %d\n", i);
      return 1;
    }
  }
  delete result;
  delete in0;
  delete in1;
  printf("PASS: infer\n");
  return 0;
}
