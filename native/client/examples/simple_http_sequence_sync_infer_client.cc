// Stateful sequence over unary HTTP calls (reference
// src/c++/examples/simple_http_sequence_sync_infer_client.cc behavior):
// correlation id + start/end flags on ordinary Infer requests, two
// interleaved sequences verified by their accumulators.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<int32_t> values{11, 7, 5, 3, 2, 0, 1};
  int32_t acc_pos = 0, acc_neg = 0;
  for (size_t i = 0; i < values.size(); ++i) {
    for (int sign : {+1, -1}) {
      int32_t v = sign * values[i];
      tc::InferInput* in;
      tc::InferInput::Create(&in, "INPUT", {1}, "INT32");
      in->AppendRaw(reinterpret_cast<const uint8_t*>(&v), sizeof(int32_t));
      tc::InferOptions options("simple_sequence");
      options.sequence_id_ = sign > 0 ? 61 : 62;
      options.sequence_start_ = (i == 0);
      options.sequence_end_ = (i == values.size() - 1);
      tc::InferResult* result = nullptr;
      err = client->Infer(&result, options, {in});
      if (!err.IsOk()) {
        fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
        return 1;
      }
      const uint8_t* buf;
      size_t len;
      err = result->RawData("OUTPUT", &buf, &len);
      if (!err.IsOk() || len < 4) {
        fprintf(stderr, "bad OUTPUT: %s\n", err.Message().c_str());
        return 1;
      }
      int32_t out;
      memcpy(&out, buf, 4);
      (sign > 0 ? acc_pos : acc_neg) = out;
      delete result;
      delete in;
    }
  }
  int32_t expected = 0;
  for (int32_t v : values) expected += v;
  if (acc_pos != expected || acc_neg != -expected) {
    fprintf(stderr, "accumulators %d/%d != ±%d\n", acc_pos, acc_neg, expected);
    return 1;
  }
  printf("PASS: http sequence sync (acc=%d/%d)\n", acc_pos, acc_neg);
  return 0;
}
