// Object reuse across requests and clients (reference
// src/c++/examples/reuse_infer_objects_client.cc behavior): the same
// InferInput/InferRequestedOutput/InferOptions objects drive repeated
// infers on both transports, with data rebinding via Reset+AppendRaw.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "http_client.h"

namespace tc = tc_tpu::client;

static bool CheckSum(tc::InferResult* r, const std::vector<int32_t>& a,
                     const std::vector<int32_t>& b) {
  const uint8_t* buf;
  size_t len;
  if (!r->RawData("OUTPUT0", &buf, &len).IsOk() ||
      len != 16 * sizeof(int32_t))
    return false;
  const int32_t* s = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i)
    if (s[i] != a[i] + b[i]) return false;
  return true;
}

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> hc;
  std::unique_ptr<tc::InferenceServerGrpcClient> gc;
  if (!tc::InferenceServerHttpClient::Create(&hc, url).IsOk() ||
      !tc::InferenceServerGrpcClient::Create(&gc, url).IsOk()) {
    fprintf(stderr, "client creation failed\n");
    return 1;
  }
  std::vector<int32_t> a(16), b(16, 3);
  for (int i = 0; i < 16; ++i) a[i] = i;
  tc::InferInput *in0, *in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  in0->AppendRaw(reinterpret_cast<const uint8_t*>(a.data()),
                 a.size() * sizeof(int32_t));
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(b.data()),
                 b.size() * sizeof(int32_t));
  tc::InferRequestedOutput *o0, *o1;
  tc::InferRequestedOutput::Create(&o0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&o1, "OUTPUT1");
  tc::InferOptions options("simple");

  for (int round = 0; round < 4; ++round) {
    tc::InferResult* r = nullptr;
    if (!hc->Infer(&r, options, {in0, in1}, {o0, o1}).IsOk() ||
        !CheckSum(r, a, b)) {
      fprintf(stderr, "http round %d failed\n", round);
      return 1;
    }
    delete r;
    if (!gc->Infer(&r, options, {in0, in1}, {o0, o1}).IsOk() ||
        !CheckSum(r, a, b)) {
      fprintf(stderr, "grpc round %d failed\n", round);
      return 1;
    }
    delete r;
    // rebind new data through the same objects
    for (auto& v : a) v += 10;
    in0->Reset();
    in0->AppendRaw(reinterpret_cast<const uint8_t*>(a.data()),
                   a.size() * sizeof(int32_t));
  }
  delete in0;
  delete in1;
  delete o0;
  delete o1;
  printf("PASS: infer object reuse across transports\n");
  return 0;
}
