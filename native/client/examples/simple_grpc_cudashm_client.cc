// C++ device-path example (reference
// src/c++/examples/simple_grpc_cudashm_client.cc behavior spec, surveyed at
// SURVEY.md §3.5): run `simple` with inputs AND outputs passing through
// registered XLA shared-memory regions — tensor bytes never ride the infer
// request/response.  Leak assertions via CudaSharedMemoryStatus mirror the
// reference's allocated_shared_memory_regions checks.

#include <algorithm>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"
#include "xla_shm_utils.h"

namespace tc = tc_tpu::client;

#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tc::Error err__ = (x);                                          \
    if (!err__.IsOk()) {                                            \
      fprintf(stderr, "%s: %s\n", (msg), err__.Message().c_str());  \
      return 1;                                                     \
    }                                                               \
  } while (0)

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int bench_iters = 0;  // -n N: timed loop, prints p50/p99 (BASELINE row:
                        // C++ xla-shm p50 parity with the Python path)
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "-n") == 0) bench_iters = atoi(argv[i + 1]);
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  FAIL_IF_ERR(tc::InferenceServerGrpcClient::Create(&client, url),
              "client creation failed");

  // start from a clean registry
  FAIL_IF_ERR(client->UnregisterCudaSharedMemory(), "unregister-all failed");

  constexpr size_t kCount = 16;
  constexpr size_t kBytes = kCount * sizeof(int32_t);

  // input regions: tensors are built IN PLACE in the mapped region (no
  // client-side memcpy) and published with Commit — the reference flow's
  // cudaMemcpy+ipc-handle becomes write-in-place + generation bump, and
  // the server caches its device import while the generation is unchanged
  int32_t input0[kCount], input1[kCount];
  tc::XlaShmHandle in0_h, in1_h, out0_h, out1_h;
  FAIL_IF_ERR(tc::CreateXlaSharedMemoryRegion(&in0_h, "input0_data", kBytes, 0),
              "create input0 region failed");
  FAIL_IF_ERR(tc::CreateXlaSharedMemoryRegion(&in1_h, "input1_data", kBytes, 0),
              "create input1 region failed");
  void *in0_p, *in1_p;
  FAIL_IF_ERR(tc::XlaSharedMemoryData(in0_h, &in0_p), "input0 data ptr");
  FAIL_IF_ERR(tc::XlaSharedMemoryData(in1_h, &in1_p), "input1 data ptr");
  for (size_t i = 0; i < kCount; ++i) {
    input0[i] = static_cast<int32_t>(i);
    input1[i] = 1;
    static_cast<int32_t*>(in0_p)[i] = input0[i];
    static_cast<int32_t*>(in1_p)[i] = input1[i];
  }
  FAIL_IF_ERR(tc::CommitXlaSharedMemoryRegion(in0_h), "commit input0");
  FAIL_IF_ERR(tc::CommitXlaSharedMemoryRegion(in1_h), "commit input1");
  FAIL_IF_ERR(
      tc::CreateXlaSharedMemoryRegion(&out0_h, "output0_data", kBytes, 0),
      "create output0 region failed");
  FAIL_IF_ERR(
      tc::CreateXlaSharedMemoryRegion(&out1_h, "output1_data", kBytes, 0),
      "create output1 region failed");

  struct Reg {
    const char* name;
    tc::XlaShmHandle* h;
  } regs[] = {{"input0_data", &in0_h},
              {"input1_data", &in1_h},
              {"output0_data", &out0_h},
              {"output1_data", &out1_h}};
  for (const auto& r : regs) {
    std::vector<uint8_t> raw;
    FAIL_IF_ERR(tc::GetXlaSharedMemoryRawHandle(*r.h, &raw),
                "raw handle failed");
    FAIL_IF_ERR(client->RegisterCudaSharedMemory(r.name, raw, 0, kBytes),
                "register failed");
  }

  // all four regions must show in status (leak assertion, part 1)
  inference::CudaSharedMemoryStatusResponse status;
  FAIL_IF_ERR(client->CudaSharedMemoryStatus(&status), "status failed");
  if (status.regions_size() != 4) {
    fprintf(stderr, "FAIL: expected 4 registered regions, got %d\n",
            status.regions_size());
    return 1;
  }

  tc::InferInput *in0, *in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  FAIL_IF_ERR(in0->SetSharedMemory("input0_data", kBytes),
              "INPUT0 set shm failed");
  FAIL_IF_ERR(in1->SetSharedMemory("input1_data", kBytes),
              "INPUT1 set shm failed");
  tc::InferRequestedOutput *out0, *out1;
  tc::InferRequestedOutput::Create(&out0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&out1, "OUTPUT1");
  FAIL_IF_ERR(out0->SetSharedMemory("output0_data", kBytes),
              "OUTPUT0 set shm failed");
  FAIL_IF_ERR(out1->SetSharedMemory("output1_data", kBytes),
              "OUTPUT1 set shm failed");

  tc::InferOptions options("simple");
  // two infers over the unchanged regions: the second is served from the
  // server's cached device import (no host copy, no DMA — asserted by the
  // harness-side stats in tests/test_native_client.py)
  for (int rep = 0; rep < 2; ++rep) {
    tc::InferResult* result = nullptr;
    FAIL_IF_ERR(client->Infer(&result, options, {in0, in1}, {out0, out1}),
                "inference failed");
    delete result;
  }

  // outputs land in the regions, not the response
  int32_t sum[kCount], diff[kCount];
  FAIL_IF_ERR(tc::GetXlaSharedMemoryContents(out0_h, sum, kBytes),
              "read output0 failed");
  FAIL_IF_ERR(tc::GetXlaSharedMemoryContents(out1_h, diff, kBytes),
              "read output1 failed");
  for (size_t i = 0; i < kCount; ++i) {
    if (sum[i] != input0[i] + input1[i] || diff[i] != input0[i] - input1[i]) {
      fprintf(stderr, "FAIL: wrong result at %zu: sum=%d diff=%d\n", i,
              sum[i], diff[i]);
      return 1;
    }
  }

  if (bench_iters > 0) {
    // timed closed loop over the unchanged regions: after the first
    // import the server serves inputs from its cached device array, so
    // per-iteration cost is request handling + execute + output D2H
    std::vector<double> lat_ms;
    lat_ms.reserve(bench_iters);
    for (int it = 0; it < bench_iters; ++it) {
      auto t0 = std::chrono::steady_clock::now();
      tc::InferResult* r = nullptr;
      FAIL_IF_ERR(client->Infer(&r, options, {in0, in1}, {out0, out1}),
                  "bench inference failed");
      delete r;
      lat_ms.push_back(std::chrono::duration<double, std::milli>(
                           std::chrono::steady_clock::now() - t0)
                           .count());
    }
    std::sort(lat_ms.begin(), lat_ms.end());
    printf("bench: %d iters, p50 %.3f ms, p99 %.3f ms\n", bench_iters,
           lat_ms[lat_ms.size() / 2],
           lat_ms[std::min(lat_ms.size() - 1,
                           static_cast<size_t>(lat_ms.size() * 99 / 100))]);
  }

  FAIL_IF_ERR(client->UnregisterCudaSharedMemory(), "unregister failed");
  FAIL_IF_ERR(client->CudaSharedMemoryStatus(&status), "status failed");
  if (status.regions_size() != 0) {
    fprintf(stderr, "FAIL: %d regions leaked after unregister\n",
            status.regions_size());
    return 1;
  }
  for (const auto& r : regs) {
    FAIL_IF_ERR(tc::DestroyXlaSharedMemoryRegion(r.h), "destroy failed");
  }
  delete in0;
  delete in1;
  delete out0;
  delete out1;

  printf("PASS: xla shm (device-path regions, zero tensor bytes on the wire)\n");
  return 0;
}
