// Image classification CLI (reference src/c++/examples/image_client.cc —
// the application-level behavioral spec, SURVEY.md §3.6, compacted):
//
// * fetches model metadata + config JSON and validates a 1-in/1-out image
//   model (CHW/HWC layout, optional batch dim),
// * builds a deterministic synthetic image batch (no image file needed, so
//   this doubles as an executable acceptance test),
// * requests top-k classification ("score:index[:label]" strings) via
//   InferRequestedOutput's class_count,
// * decodes the length-prefixed BYTES classification tensor.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"
#include "json.h"

namespace tc = tc_tpu::client;
namespace js = tc_tpu::json;

struct ModelInfo {
  std::string input_name, output_name, dtype, layout;
  int64_t c = 0, h = 0, w = 0;
  int max_batch = 0;
};

static bool ParseModel(
    const std::string& meta_json, const std::string& config_json,
    ModelInfo* info, std::string* why) {
  js::Value meta, config;
  std::string err;
  if (!js::Parse(meta_json, &meta, &err) ||
      !js::Parse(config_json, &config, &err)) {
    *why = "bad JSON: " + err;
    return false;
  }
  const auto& inputs = meta.At("inputs").AsArray();
  const auto& outputs = meta.At("outputs").AsArray();
  if (inputs.size() != 1 || outputs.size() != 1) {
    *why = "expecting 1 input / 1 output";
    return false;
  }
  info->input_name = inputs[0].At("name").AsString();
  info->output_name = outputs[0].At("name").AsString();
  info->dtype = inputs[0].At("datatype").AsString();
  info->max_batch =
      static_cast<int>(config.At("max_batch_size").AsInt());
  std::vector<int64_t> shape;
  for (const auto& d : inputs[0].At("shape").AsArray())
    shape.push_back(d.AsInt());
  if (info->max_batch > 0) shape.erase(shape.begin());
  if (shape.size() != 3) {
    *why = "expecting input rank 3";
    return false;
  }
  if (shape[0] == 1 || shape[0] == 3) {
    info->layout = "CHW";
    info->c = shape[0];
    info->h = shape[1];
    info->w = shape[2];
  } else if (shape[2] == 1 || shape[2] == 3) {
    info->layout = "HWC";
    info->h = shape[0];
    info->w = shape[1];
    info->c = shape[2];
  } else {
    *why = "cannot infer CHW/HWC layout";
    return false;
  }
  if (info->dtype != "FP32") {
    *why = "expecting FP32 input, got " + info->dtype;
    return false;
  }
  return true;
}

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  std::string model_name = "simple_cnn";
  int batch = 1, classes = 3;
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "-m") == 0) model_name = argv[i + 1];
    if (strcmp(argv[i], "-b") == 0) batch = atoi(argv[i + 1]);
    if (strcmp(argv[i], "-c") == 0) classes = atoi(argv[i + 1]);
  }

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }

  std::string meta_json, config_json;
  err = client->ModelMetadata(&meta_json, model_name);
  if (err.IsOk()) err = client->ModelConfig(&config_json, model_name);
  if (!err.IsOk()) {
    fprintf(stderr, "metadata/config failed: %s\n", err.Message().c_str());
    return 1;
  }
  ModelInfo info;
  std::string why;
  if (!ParseModel(meta_json, config_json, &info, &why)) {
    fprintf(stderr, "model validation failed: %s\n", why.c_str());
    return 1;
  }
  if (info.max_batch == 0) batch = 1;

  // deterministic synthetic image batch
  const size_t pixels = static_cast<size_t>(info.c * info.h * info.w);
  std::vector<float> data(static_cast<size_t>(batch) * pixels);
  for (size_t i = 0; i < data.size(); ++i)
    data[i] = static_cast<float>((i * 2654435761u) % 255) / 127.5f - 1.0f;

  std::vector<int64_t> shape;
  if (info.max_batch > 0) shape.push_back(batch);
  if (info.layout == "CHW") {
    shape.insert(shape.end(), {info.c, info.h, info.w});
  } else {
    shape.insert(shape.end(), {info.h, info.w, info.c});
  }
  tc::InferInput* in;
  tc::InferInput::Create(&in, info.input_name, shape, "FP32");
  in->AppendRaw(reinterpret_cast<const uint8_t*>(data.data()),
                data.size() * sizeof(float));
  tc::InferRequestedOutput* out;
  tc::InferRequestedOutput::Create(&out, info.output_name,
                                   static_cast<size_t>(classes));

  tc::InferOptions options(model_name);
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {in}, {out});
  if (!err.IsOk()) {
    fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
    return 1;
  }

  // classification tensor: length-prefixed "score:index[:label]" strings
  const uint8_t* buf;
  size_t len;
  err = result->RawData(info.output_name, &buf, &len);
  if (!err.IsOk()) {
    fprintf(stderr, "bad classification output: %s\n", err.Message().c_str());
    return 1;
  }
  size_t off = 0;
  int n_strings = 0;
  int expect = batch * classes;
  while (off + 4 <= len && n_strings < expect) {
    uint32_t slen;
    memcpy(&slen, buf + off, 4);
    off += 4;
    if (off + slen > len) {
      fprintf(stderr, "truncated classification string\n");
      return 1;
    }
    std::string s(reinterpret_cast<const char*>(buf + off), slen);
    off += slen;
    if (n_strings % classes == 0)
      printf("Image %d:\n", n_strings / classes);
    printf("    %s\n", s.c_str());
    // sanity: leading float score then ':'
    if (s.find(':') == std::string::npos) {
      fprintf(stderr, "malformed classification '%s'\n", s.c_str());
      return 1;
    }
    ++n_strings;
  }
  if (n_strings != expect) {
    fprintf(stderr, "expected %d classification strings, got %d\n", expect,
            n_strings);
    return 1;
  }
  delete result;
  delete out;
  delete in;
  printf("PASS: image client\n");
  return 0;
}
