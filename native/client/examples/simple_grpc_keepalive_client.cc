// Keepalive-configured channel (reference
// src/c++/examples/simple_grpc_keepalive_client.cc behavior): create the
// client with KeepAliveOptions, then run the standard simple sum/diff
// verification. On this transport the options become TCP keepalive probes.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  tc::KeepAliveOptions keepalive;
  // defaults match the reference example's flags
  keepalive.keepalive_time_ms = 10000;
  keepalive.keepalive_timeout_ms = 2000;
  keepalive.keepalive_permit_without_calls = true;
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "--grpc-keepalive-time") == 0)
      keepalive.keepalive_time_ms = atoi(argv[i + 1]);
    if (strcmp(argv[i], "--grpc-keepalive-timeout") == 0)
      keepalive.keepalive_timeout_ms = atoi(argv[i + 1]);
  }

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err =
      tc::InferenceServerGrpcClient::Create(&client, url, false, keepalive);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }

  std::vector<int32_t> in0(16), in1(16);
  for (int i = 0; i < 16; ++i) {
    in0[i] = i;
    in1[i] = 1;
  }
  tc::InferInput *i0, *i1;
  tc::InferInput::Create(&i0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&i1, "INPUT1", {1, 16}, "INT32");
  i0->AppendRaw(reinterpret_cast<const uint8_t*>(in0.data()), 16 * 4);
  i1->AppendRaw(reinterpret_cast<const uint8_t*>(in1.data()), 16 * 4);

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  // two back-to-back RPCs so the second rides the kept-alive pooled socket
  for (int round = 0; round < 2; ++round) {
    err = client->Infer(&result, options, {i0, i1});
    if (!err.IsOk()) {
      fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
      return 1;
    }
    const uint8_t* buf;
    size_t len;
    if (!result->RawData("OUTPUT0", &buf, &len).IsOk() || len != 64) {
      fprintf(stderr, "bad OUTPUT0\n");
      return 1;
    }
    const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
    for (int i = 0; i < 16; ++i) {
      if (sums[i] != in0[i] + in1[i]) {
        fprintf(stderr, "sum mismatch at %d: %d\n", i, sums[i]);
        return 1;
      }
    }
    if (round == 0) delete result;
  }
  delete result;
  delete i0;
  delete i1;
  printf("PASS: grpc keepalive\n");
  return 0;
}
