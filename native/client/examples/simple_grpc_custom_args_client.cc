// Custom channel arguments (reference
// src/c++/examples/simple_grpc_custom_args_client.cc:105-116): build a
// ChannelArguments with message-size caps and keepalive args, create the
// client from it, and run the simple sum/diff verification. Also proves the
// receive cap is enforced by requesting one with a tiny limit.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

static tc::Error RunSimple(tc::InferenceServerGrpcClient* client) {
  std::vector<int32_t> in0(16), in1(16);
  for (int i = 0; i < 16; ++i) {
    in0[i] = i;
    in1[i] = 1;
  }
  tc::InferInput *i0, *i1;
  tc::InferInput::Create(&i0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&i1, "INPUT1", {1, 16}, "INT32");
  i0->AppendRaw(reinterpret_cast<const uint8_t*>(in0.data()), 16 * 4);
  i1->AppendRaw(reinterpret_cast<const uint8_t*>(in1.data()), 16 * 4);
  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  tc::Error err = client->Infer(&result, options, {i0, i1});
  if (err.IsOk()) {
    const uint8_t* buf;
    size_t len;
    err = result->RawData("OUTPUT0", &buf, &len);
    if (err.IsOk()) {
      const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
      for (int i = 0; i < 16; ++i)
        if (sums[i] != in0[i] + in1[i]) err = tc::Error("sum mismatch");
    }
  }
  delete result;
  delete i0;
  delete i1;
  return err;
}

int main(int argc, char** argv) {
  std::string url = "localhost:8001";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  // the reference example's argument set
  tc::ChannelArguments channel_args;
  channel_args.SetMaxSendMessageSize(1024 * 1024);
  channel_args.SetMaxReceiveMessageSize(1024 * 1024);
  channel_args.SetInt("grpc.keepalive_time_ms", 10000);
  channel_args.SetInt("grpc.keepalive_timeout_ms", 2000);
  channel_args.SetInt("grpc.keepalive_permit_without_calls", 1);
  channel_args.SetInt("grpc.http2.max_pings_without_data", 2);

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err =
      tc::InferenceServerGrpcClient::Create(&client, url, channel_args);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  err = RunSimple(client.get());
  if (!err.IsOk()) {
    fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
    return 1;
  }

  // a 64-byte receive cap must reject the same response
  tc::ChannelArguments tiny;
  tiny.SetMaxReceiveMessageSize(64);
  std::unique_ptr<tc::InferenceServerGrpcClient> capped;
  err = tc::InferenceServerGrpcClient::Create(&capped, url, tiny);
  if (!err.IsOk()) {
    fprintf(stderr, "capped client creation failed: %s\n",
            err.Message().c_str());
    return 1;
  }
  err = RunSimple(capped.get());
  if (err.IsOk() ||
      err.Message().find("maximum receive message size") == std::string::npos) {
    fprintf(stderr, "expected receive-cap rejection, got: %s\n",
            err.Message().c_str());
    return 1;
  }

  printf("PASS: grpc custom args\n");
  return 0;
}
