// Server-side generation from C++ over the decoupled duplex stream
// (framework extension mirrored from examples/simple_http_generate_client.py):
// ONE request carrying the prompt (BYTES) + max_tokens parameter; the server
// runs the whole KV-cache decode loop and streams a token per response.

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  std::string prompt = "In a hole in the ground";
  int n_tokens = 4;
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "-p") == 0) prompt = argv[i + 1];
    if (strcmp(argv[i], "-n") == 0) n_tokens = atoi(argv[i + 1]);
  }

  // declared BEFORE the client: the stream callback captures these, and
  // the client's destructor joins its reader thread — reverse destruction
  // order must tear the client down first
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> token_ids;
  std::string text;
  size_t text_frames = 0;
  bool got_final = false, stream_error = false;

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  err = client->StartStream([&](tc::InferResult* r) {
    std::lock_guard<std::mutex> lk(mu);
    bool is_final = false, is_null = false;
    r->IsFinalResponse(&is_final);
    r->IsNullResponse(&is_null);
    if (is_final) got_final = true;
    if (!is_null) {
      if (!r->RequestStatus().IsOk()) {
        fprintf(stderr, "stream error: %s\n",
                r->RequestStatus().Message().c_str());
        stream_error = true;
      } else {
        const uint8_t* buf;
        size_t len;
        if (r->RawData("token_id", &buf, &len).IsOk() && len >= 4) {
          int32_t tok;
          memcpy(&tok, buf, 4);
          token_ids.push_back(tok);
        }
        // BYTES wire format: <u32 length><utf-8 chars>
        if (r->RawData("text_output", &buf, &len).IsOk() && len >= 4) {
          uint32_t slen;
          memcpy(&slen, buf, 4);
          if (slen <= len - 4) {
            // one frame per token; a char may be 1-2 UTF-8 bytes
            text.append(reinterpret_cast<const char*>(buf + 4), slen);
            ++text_frames;
          }
        }
      }
    }
    cv.notify_all();
    delete r;
  });
  if (!err.IsOk()) {
    fprintf(stderr, "stream start failed: %s\n", err.Message().c_str());
    return 1;
  }

  tc::InferInput* tin;
  tc::InferInput::Create(&tin, "text_input", {1}, "BYTES");
  tin->AppendFromString({prompt});
  tc::InferOptions options("llama_generate");
  options.triton_enable_empty_final_response_ = true;
  options.request_parameters_["max_tokens"] = std::to_string(n_tokens);
  err = client->AsyncStreamInfer(options, {tin});
  if (!err.IsOk()) {
    fprintf(stderr, "stream infer failed: %s\n", err.Message().c_str());
    client->FinishStream();
    return 1;
  }

  bool timed_out = false;
  {
    std::unique_lock<std::mutex> lk(mu);
    // the server clamps max_tokens to its window capacity, so wait for
    // the final flag and validate the count afterwards
    timed_out = !cv.wait_for(lk, std::chrono::seconds(120), [&] {
      return stream_error || got_final;
    });
  }
  client->FinishStream();  // joins the reader thread before locals die
  delete tin;
  if (stream_error) return 1;
  if (timed_out) {
    fprintf(stderr, "timed out: %zu/%d tokens\n", token_ids.size(), n_tokens);
    return 1;
  }
  if (token_ids.empty() || token_ids.size() != text_frames) {
    fprintf(stderr, "inconsistent stream: %zu ids, %zu text frames\n",
            token_ids.size(), text_frames);
    return 1;
  }
  printf("prompt: \"%s\"\n", prompt.c_str());
  printf("generated %zu tokens, text bytes: %zu\n", token_ids.size(),
         text.size());
  printf("PASS: generate stream\n");
  return 0;
}
