// Decoupled model over the bidi stream: one request, N responses
// (reference src/c++/examples/simple_grpc_custom_repeat.cc behavior against
// the repeat backend).

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  int repeat = 5;
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
    if (strcmp(argv[i], "-r") == 0) repeat = atoi(argv[i + 1]);
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::mutex mu;
  std::condition_variable cv;
  std::vector<int32_t> received;
  bool got_final = false;
  err = client->StartStream([&](tc::InferResult* r) {
    std::lock_guard<std::mutex> lk(mu);
    bool is_final = false, is_null = false;
    r->IsFinalResponse(&is_final);
    r->IsNullResponse(&is_null);
    if (is_final) got_final = true;
    const uint8_t* buf;
    size_t len;
    if (!is_null && r->RequestStatus().IsOk() &&
        r->RawData("OUT", &buf, &len).IsOk() && len >= 4) {
      int32_t v;
      memcpy(&v, buf, 4);
      received.push_back(v);
    }
    cv.notify_all();
    delete r;
  });
  if (!err.IsOk()) {
    fprintf(stderr, "stream start failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<int32_t> values(repeat);
  std::vector<uint32_t> delays(repeat, 500);
  for (int i = 0; i < repeat; ++i) values[i] = 10 * (i + 1);
  uint32_t wait = 0;
  tc::InferInput *vin, *din, *win;
  tc::InferInput::Create(&vin, "IN", {repeat}, "INT32");
  vin->AppendRaw(reinterpret_cast<const uint8_t*>(values.data()),
                 values.size() * sizeof(int32_t));
  tc::InferInput::Create(&din, "DELAY", {repeat}, "UINT32");
  din->AppendRaw(reinterpret_cast<const uint8_t*>(delays.data()),
                 delays.size() * sizeof(uint32_t));
  tc::InferInput::Create(&win, "WAIT", {1}, "UINT32");
  win->AppendRaw(reinterpret_cast<const uint8_t*>(&wait), sizeof(uint32_t));
  tc::InferOptions options("repeat_int32");
  options.triton_enable_empty_final_response_ = true;
  err = client->AsyncStreamInfer(options, {vin, din, win});
  if (!err.IsOk()) {
    fprintf(stderr, "stream infer failed: %s\n", err.Message().c_str());
    client->FinishStream();  // join the reader before locals go away
    return 1;
  }
  bool timed_out = false;
  {
    std::unique_lock<std::mutex> lk(mu);
    timed_out = !cv.wait_for(lk, std::chrono::seconds(60), [&] {
      return static_cast<int>(received.size()) == repeat && got_final;
    });
  }
  // Always close the stream (joins the reader thread) BEFORE any return:
  // the callback captures locals declared after `client`, which would be
  // destroyed first on an early return.
  client->FinishStream();
  if (timed_out) {
    fprintf(stderr, "timed out: %zu/%d responses\n", received.size(), repeat);
    return 1;
  }
  for (int i = 0; i < repeat; ++i) {
    if (received[i] != values[i]) {
      fprintf(stderr, "mismatch at %d\n", i);
      return 1;
    }
  }
  delete vin;
  delete din;
  delete win;
  printf("PASS: grpc custom repeat (%d responses + final)\n", repeat);
  return 0;
}
