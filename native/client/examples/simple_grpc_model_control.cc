// Model repository control over gRPC (reference
// src/c++/examples/simple_grpc_model_control.cc behavior): unload, verify
// not-ready, reload, verify ready, inspect the index.

#include <cstdio>
#include <cstring>
#include <string>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  const std::string model = "identity_fp32";
  bool ready = false;
  if (!client->UnloadModel(model).IsOk()) {
    fprintf(stderr, "unload failed\n");
    return 1;
  }
  if (!client->IsModelReady(&ready, model).IsOk()) {
    fprintf(stderr, "IsModelReady RPC failed\n");
    return 1;
  }
  if (ready) {
    fprintf(stderr, "model still ready after unload\n");
    return 1;
  }
  if (!client->LoadModel(model).IsOk()) {
    fprintf(stderr, "load failed\n");
    return 1;
  }
  if (!client->IsModelReady(&ready, model).IsOk()) {
    fprintf(stderr, "IsModelReady RPC failed\n");
    return 1;
  }
  if (!ready) {
    fprintf(stderr, "model not ready after load\n");
    return 1;
  }
  tc::pb::RepositoryIndexResponse index;
  if (!client->ModelRepositoryIndex(&index).IsOk() ||
      index.models_size() == 0) {
    fprintf(stderr, "repository index failed\n");
    return 1;
  }
  bool found = false;
  for (const auto& m : index.models())
    if (m.name() == model && m.state() == "READY") found = true;
  if (!found) {
    fprintf(stderr, "model missing from index\n");
    return 1;
  }
  printf("PASS: grpc model control\n");
  return 0;
}
