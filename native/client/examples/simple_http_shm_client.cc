// C++ system-shm example (reference src/c++/examples/simple_http_shm_client.cc
// behavior): create/map POSIX shm, register, infer with shm inputs+outputs,
// read results from the region, unregister/unlink.

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
  }
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  client->UnregisterSystemSharedMemory();

  const size_t nbytes = 16 * sizeof(int32_t);
  const char* in_key = "/cc_input_shm";
  const char* out_key = "/cc_output_shm";

  // create + map the input region (both tensors at offsets)
  shm_unlink(in_key);
  shm_unlink(out_key);
  int in_fd = shm_open(in_key, O_RDWR | O_CREAT, 0600);
  int out_fd = shm_open(out_key, O_RDWR | O_CREAT, 0600);
  if (in_fd < 0 || out_fd < 0 || ftruncate(in_fd, 2 * nbytes) != 0 ||
      ftruncate(out_fd, 2 * nbytes) != 0) {
    fprintf(stderr, "shm setup failed\n");
    return 1;
  }
  int32_t* in_ptr = static_cast<int32_t*>(mmap(
      nullptr, 2 * nbytes, PROT_READ | PROT_WRITE, MAP_SHARED, in_fd, 0));
  int32_t* out_ptr = static_cast<int32_t*>(mmap(
      nullptr, 2 * nbytes, PROT_READ | PROT_WRITE, MAP_SHARED, out_fd, 0));
  if (in_ptr == MAP_FAILED || out_ptr == MAP_FAILED) {
    fprintf(stderr, "mmap failed\n");
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    in_ptr[i] = i;       // INPUT0 at offset 0
    in_ptr[16 + i] = 1;  // INPUT1 at offset nbytes
  }

  err = client->RegisterSystemSharedMemory("input_data", in_key, 2 * nbytes);
  if (!err.IsOk()) {
    fprintf(stderr, "register input failed: %s\n", err.Message().c_str());
    return 1;
  }
  err = client->RegisterSystemSharedMemory("output_data", out_key, 2 * nbytes);
  if (!err.IsOk()) {
    fprintf(stderr, "register output failed: %s\n", err.Message().c_str());
    return 1;
  }

  tc::InferInput* in0;
  tc::InferInput* in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  in0->SetSharedMemory("input_data", nbytes, 0);
  in1->SetSharedMemory("input_data", nbytes, nbytes);
  tc::InferRequestedOutput* out0;
  tc::InferRequestedOutput* out1;
  tc::InferRequestedOutput::Create(&out0, "OUTPUT0");
  tc::InferRequestedOutput::Create(&out1, "OUTPUT1");
  out0->SetSharedMemory("output_data", nbytes, 0);
  out1->SetSharedMemory("output_data", nbytes, nbytes);

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {in0, in1}, {out0, out1});
  if (!err.IsOk()) {
    fprintf(stderr, "inference failed: %s\n", err.Message().c_str());
    return 1;
  }
  for (int i = 0; i < 16; ++i) {
    if (out_ptr[i] != in_ptr[i] + in_ptr[16 + i]) {
      fprintf(stderr, "sum mismatch at %d\n", i);
      return 1;
    }
    if (out_ptr[16 + i] != in_ptr[i] - in_ptr[16 + i]) {
      fprintf(stderr, "diff mismatch at %d\n", i);
      return 1;
    }
  }

  std::string status;
  client->SystemSharedMemoryStatus(&status);
  if (status.find("input_data") == std::string::npos) {
    fprintf(stderr, "region missing from status: %s\n", status.c_str());
    return 1;
  }
  client->UnregisterSystemSharedMemory();

  delete result;
  delete in0;
  delete in1;
  delete out0;
  delete out1;
  munmap(in_ptr, 2 * nbytes);
  munmap(out_ptr, 2 * nbytes);
  close(in_fd);
  close(out_fd);
  shm_unlink(in_key);
  shm_unlink(out_key);
  printf("PASS: system shared memory\n");
  return 0;
}
