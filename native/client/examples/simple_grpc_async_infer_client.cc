// Async inference with callback over gRPC (reference
// src/c++/examples/simple_grpc_async_infer_client.cc behavior).

#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<int32_t> input0(16), input1(16);
  for (int i = 0; i < 16; ++i) {
    input0[i] = i;
    input1[i] = 1;
  }
  tc::InferInput *in0, *in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  in0->AppendRaw(reinterpret_cast<const uint8_t*>(input0.data()),
                 input0.size() * sizeof(int32_t));
  in1->AppendRaw(reinterpret_cast<const uint8_t*>(input1.data()),
                 input1.size() * sizeof(int32_t));

  std::mutex mu;
  std::condition_variable cv;
  int done = 0;
  bool ok = true;
  tc::InferOptions options("simple");
  const int kRequests = 4;
  int submitted = 0;
  tc::Error submit_err;
  for (int r = 0; r < kRequests; ++r) {
    err = client->AsyncInfer(
        [&](tc::InferResult* result) {
          std::lock_guard<std::mutex> lk(mu);
          const uint8_t* buf;
          size_t len;
          if (!result->RequestStatus().IsOk() ||
              !result->RawData("OUTPUT0", &buf, &len).IsOk() ||
              len != 16 * sizeof(int32_t) ||
              reinterpret_cast<const int32_t*>(buf)[5] != 6) {
            ok = false;
          }
          ++done;
          delete result;
          cv.notify_one();
        },
        options, {in0, in1});
    if (!err.IsOk()) {
      submit_err = err;
      break;
    }
    ++submitted;
  }
  // Drain every accepted request before returning — the callbacks capture
  // locals that are destroyed before the client joins its workers.
  {
    std::unique_lock<std::mutex> lk(mu);
    cv.wait(lk, [&] { return done == submitted; });
  }
  if (!submit_err.IsOk()) {
    fprintf(stderr, "async submit failed: %s\n", submit_err.Message().c_str());
    return 1;
  }
  delete in0;
  delete in1;
  if (!ok) {
    fprintf(stderr, "async result mismatch\n");
    return 1;
  }
  printf("PASS: grpc async infer (%d requests)\n", kRequests);
  return 0;
}
