// BYTES tensor round trip over gRPC (reference
// src/c++/examples/simple_grpc_string_infer_client.cc behavior, against the
// harness's BYTES echo model).

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<std::string> values{"hello", "", "wörld", std::string(300, 'x')};
  tc::InferInput* input;
  tc::InferInput::Create(&input, "INPUT0", {1, 4}, "BYTES");
  err = input->AppendFromString(values);
  if (!err.IsOk()) {
    fprintf(stderr, "append failed: %s\n", err.Message().c_str());
    return 1;
  }
  tc::InferOptions options("simple_identity");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {input});
  if (!err.IsOk()) {
    fprintf(stderr, "inference failed: %s\n", err.Message().c_str());
    return 1;
  }
  std::vector<std::string> echoed;
  err = result->StringData("OUTPUT0", &echoed);
  if (!err.IsOk() || echoed.size() != values.size()) {
    fprintf(stderr, "string decode failed\n");
    return 1;
  }
  for (size_t i = 0; i < values.size(); ++i) {
    if (echoed[i] != values[i]) {
      fprintf(stderr, "mismatch at %zu\n", i);
      return 1;
    }
  }
  delete result;
  delete input;
  printf("PASS: grpc string infer\n");
  return 0;
}
