// HTTP variant of the device-path example (reference
// src/c++/examples/simple_http_cudashm_client.cc behavior): XLA shm regions
// registered over the REST API, inputs and outputs passed by region name.

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"
#include "xla_shm_utils.h"

namespace tc = tc_tpu::client;

#define FAIL_IF_ERR(x, msg)                                         \
  do {                                                              \
    tc::Error err__ = (x);                                          \
    if (!err__.IsOk()) {                                            \
      fprintf(stderr, "%s: %s\n", (msg), err__.Message().c_str());  \
      return 1;                                                     \
    }                                                               \
  } while (0)

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
  }
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  FAIL_IF_ERR(tc::InferenceServerHttpClient::Create(&client, url),
              "client creation failed");
  FAIL_IF_ERR(client->UnregisterCudaSharedMemory(), "unregister-all failed");

  constexpr size_t kCount = 16;
  constexpr size_t kBytes = kCount * sizeof(int32_t);
  int32_t input0[kCount], input1[kCount];
  for (size_t i = 0; i < kCount; ++i) {
    input0[i] = static_cast<int32_t>(i);
    input1[i] = 3;
  }

  tc::XlaShmHandle in0_h, in1_h, out_h;
  FAIL_IF_ERR(
      tc::CreateXlaSharedMemoryRegion(&in0_h, "h_input0_data", kBytes, 0),
      "create input0 region failed");
  FAIL_IF_ERR(
      tc::CreateXlaSharedMemoryRegion(&in1_h, "h_input1_data", kBytes, 0),
      "create input1 region failed");
  FAIL_IF_ERR(
      tc::CreateXlaSharedMemoryRegion(&out_h, "h_output_data", kBytes, 0),
      "create output region failed");
  FAIL_IF_ERR(tc::SetXlaSharedMemoryRegion(in0_h, input0, kBytes),
              "set input0 failed");
  FAIL_IF_ERR(tc::SetXlaSharedMemoryRegion(in1_h, input1, kBytes),
              "set input1 failed");

  struct Reg {
    const char* name;
    tc::XlaShmHandle* h;
  } regs[] = {{"h_input0_data", &in0_h},
              {"h_input1_data", &in1_h},
              {"h_output_data", &out_h}};
  for (const auto& r : regs) {
    std::vector<uint8_t> raw;
    FAIL_IF_ERR(tc::GetXlaSharedMemoryRawHandle(*r.h, &raw),
                "raw handle failed");
    FAIL_IF_ERR(client->RegisterCudaSharedMemory(r.name, raw, 0, kBytes),
                "register failed");
  }

  std::string status;
  FAIL_IF_ERR(client->CudaSharedMemoryStatus(&status), "status failed");
  for (const auto& r : regs) {
    if (status.find(r.name) == std::string::npos) {
      fprintf(stderr, "FAIL: region %s missing from status\n", r.name);
      return 1;
    }
  }

  tc::InferInput *in0, *in1;
  tc::InferInput::Create(&in0, "INPUT0", {1, 16}, "INT32");
  tc::InferInput::Create(&in1, "INPUT1", {1, 16}, "INT32");
  FAIL_IF_ERR(in0->SetSharedMemory("h_input0_data", kBytes),
              "INPUT0 set shm failed");
  FAIL_IF_ERR(in1->SetSharedMemory("h_input1_data", kBytes),
              "INPUT1 set shm failed");
  tc::InferRequestedOutput* out0;
  tc::InferRequestedOutput::Create(&out0, "OUTPUT0");
  FAIL_IF_ERR(out0->SetSharedMemory("h_output_data", kBytes),
              "OUTPUT0 set shm failed");

  tc::InferOptions options("simple");
  tc::InferResult* result = nullptr;
  FAIL_IF_ERR(client->Infer(&result, options, {in0, in1}, {out0}),
              "inference failed");
  delete result;

  int32_t sum[kCount];
  FAIL_IF_ERR(tc::GetXlaSharedMemoryContents(out_h, sum, kBytes),
              "read output failed");
  for (size_t i = 0; i < kCount; ++i) {
    if (sum[i] != input0[i] + input1[i]) {
      fprintf(stderr, "FAIL: wrong sum at %zu: %d\n", i, sum[i]);
      return 1;
    }
  }

  FAIL_IF_ERR(client->UnregisterCudaSharedMemory(), "unregister failed");
  for (const auto& r : regs) {
    FAIL_IF_ERR(tc::DestroyXlaSharedMemoryRegion(r.h), "destroy failed");
  }
  delete in0;
  delete in1;
  delete out0;

  printf("PASS: http xla shm\n");
  return 0;
}
