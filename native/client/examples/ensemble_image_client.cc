// Ensemble DAG inference from C++ (reference
// src/c++/examples/ensemble_image_client.cc behavior: the client sends raw
// tensors and the server executes the multi-step pipeline declared in
// ensemble_scheduling; here the zoo's scale->sum/diff ensemble).

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }

  std::vector<int32_t> raw0(16), raw1(16);
  for (int i = 0; i < 16; ++i) {
    raw0[i] = i;
    raw1[i] = 1;
  }
  tc::InferInput *i0, *i1;
  tc::InferInput::Create(&i0, "RAW0", {1, 16}, "INT32");
  tc::InferInput::Create(&i1, "RAW1", {1, 16}, "INT32");
  i0->AppendRaw(reinterpret_cast<const uint8_t*>(raw0.data()), 16 * 4);
  i1->AppendRaw(reinterpret_cast<const uint8_t*>(raw1.data()), 16 * 4);
  tc::InferRequestedOutput *sum_out, *diff_out;
  tc::InferRequestedOutput::Create(&sum_out, "SUM");
  tc::InferRequestedOutput::Create(&diff_out, "DIFF");

  tc::InferOptions options("ensemble_scale_sum");
  tc::InferResult* result = nullptr;
  err = client->Infer(&result, options, {i0, i1}, {sum_out, diff_out});
  if (!err.IsOk()) {
    fprintf(stderr, "infer failed: %s\n", err.Message().c_str());
    return 1;
  }

  // the ensemble scales RAW0 by two before the sum/diff member
  const uint8_t* buf;
  size_t len;
  if (!result->RawData("SUM", &buf, &len).IsOk() || len != 64) {
    fprintf(stderr, "bad SUM\n");
    return 1;
  }
  const int32_t* sums = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (sums[i] != raw0[i] * 2 + raw1[i]) {
      fprintf(stderr, "ensemble sum mismatch at %d: %d\n", i, sums[i]);
      return 1;
    }
  }
  if (!result->RawData("DIFF", &buf, &len).IsOk() || len != 64) {
    fprintf(stderr, "bad DIFF\n");
    return 1;
  }
  const int32_t* diffs = reinterpret_cast<const int32_t*>(buf);
  for (int i = 0; i < 16; ++i) {
    if (diffs[i] != raw0[i] * 2 - raw1[i]) {
      fprintf(stderr, "ensemble diff mismatch at %d: %d\n", i, diffs[i]);
      return 1;
    }
  }
  delete result;
  delete sum_out;
  delete diff_out;
  delete i0;
  delete i1;
  printf("PASS: ensemble image\n");
  return 0;
}
