// C++ sequence streaming example (reference
// src/c++/examples/simple_grpc_sequence_stream_infer_client.cc behavior):
// TWO sequences interleaved over ONE live stream.  Each response must arrive
// while the stream is still open — this only passes with real duplex
// streaming, not store-and-forward.

#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <vector>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

namespace {

struct StreamResults {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<int32_t> values;  // accumulator outputs in arrival order
  int errors = 0;

  void Push(tc::InferResult* result) {
    std::lock_guard<std::mutex> lk(mu);
    if (!result->RequestStatus().IsOk()) {
      fprintf(stderr, "stream error: %s\n",
              result->RequestStatus().Message().c_str());
      ++errors;
    } else {
      const uint8_t* buf;
      size_t len;
      result->RawData("OUTPUT", &buf, &len);
      values.push_back(*reinterpret_cast<const int32_t*>(buf));
    }
    delete result;
    cv.notify_all();
  }

  // Wait until n results arrived (returns false on timeout).
  bool WaitFor(size_t n) {
    std::unique_lock<std::mutex> lk(mu);
    return cv.wait_for(lk, std::chrono::seconds(10),
                       [&] { return values.size() + errors >= n; });
  }
};

tc::Error SendValue(
    tc::InferenceServerGrpcClient* client, uint64_t seq_id, int32_t value,
    bool start, bool end) {
  tc::InferOptions options("simple_sequence");
  options.sequence_id_ = seq_id;
  options.sequence_start_ = start;
  options.sequence_end_ = end;
  tc::InferInput* input;
  tc::InferInput::Create(&input, "INPUT", {1}, "INT32");
  input->AppendRaw(reinterpret_cast<const uint8_t*>(&value), sizeof(value));
  tc::Error err = client->AsyncStreamInfer(options, {input});
  delete input;
  return err;
}

}  // namespace

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i) {
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];
  }
  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }

  StreamResults results;
  err = client->StartStream(
      [&results](tc::InferResult* r) { results.Push(r); });
  if (!err.IsOk()) {
    fprintf(stderr, "StartStream failed: %s\n", err.Message().c_str());
    return 1;
  }

  // Interleave two sequences (ids 99 and 100, values 1..3 and 10..30) and
  // REQUIRE each round's responses before sending the next round: proof the
  // responses flow while the request side of the stream is still open.
  const uint64_t kSeqA = 99, kSeqB = 100;
  const int kSteps = 3;
  int32_t a_val[kSteps] = {1, 2, 3};
  int32_t b_val[kSteps] = {10, 20, 30};
  size_t expected = 0;
  for (int step = 0; step < kSteps; ++step) {
    bool start = step == 0;
    bool end = step == kSteps - 1;
    if (!(err = SendValue(client.get(), kSeqA, a_val[step], start, end)).IsOk() ||
        !(err = SendValue(client.get(), kSeqB, b_val[step], start, end)).IsOk()) {
      fprintf(stderr, "AsyncStreamInfer failed: %s\n", err.Message().c_str());
      return 1;
    }
    expected += 2;
    if (!results.WaitFor(expected)) {
      fprintf(stderr,
              "FAIL: responses for round %d did not arrive while the stream "
              "was open (store-and-forward streaming?)\n",
              step);
      return 1;
    }
  }

  err = client->FinishStream();
  if (!err.IsOk()) {
    fprintf(stderr, "FinishStream failed: %s\n", err.Message().c_str());
    return 1;
  }
  if (results.errors != 0) {
    fprintf(stderr, "FAIL: %d stream errors\n", results.errors);
    return 1;
  }

  // Per-sequence accumulators: A = 1,3,6 ; B = 10,30,60, interleaved in
  // arrival order per round.
  std::vector<int32_t> want = {1, 10, 3, 30, 6, 60};
  if (results.values.size() != want.size()) {
    fprintf(stderr, "FAIL: expected %zu responses, got %zu\n", want.size(),
            results.values.size());
    return 1;
  }
  for (size_t i = 0; i < want.size(); i += 2) {
    // within a round the two sequences' responses may arrive in any order
    int32_t x = results.values[i], y = results.values[i + 1];
    if (!((x == want[i] && y == want[i + 1]) ||
          (x == want[i + 1] && y == want[i]))) {
      fprintf(stderr, "FAIL: round %zu got (%d,%d), want (%d,%d)\n", i / 2, x,
              y, want[i], want[i + 1]);
      return 1;
    }
  }

  printf("PASS: sequence stream (interleaved, live responses)\n");
  return 0;
}
