// Health + metadata surface over gRPC (reference
// src/c++/examples/simple_grpc_health_metadata.cc behavior).

#include <cstdio>
#include <cstring>
#include <string>

#include "grpc_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerGrpcClient> client;
  tc::Error err = tc::InferenceServerGrpcClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  bool live = false, ready = false, model_ready = false;
  if (!client->IsServerLive(&live).IsOk() || !live) {
    fprintf(stderr, "server not live\n");
    return 1;
  }
  if (!client->IsServerReady(&ready).IsOk() || !ready) {
    fprintf(stderr, "server not ready\n");
    return 1;
  }
  if (!client->IsModelReady(&model_ready, "simple").IsOk() || !model_ready) {
    fprintf(stderr, "model not ready\n");
    return 1;
  }
  tc::pb::ServerMetadataResponse server_md;
  if (!client->ServerMetadata(&server_md).IsOk() || server_md.name().empty()) {
    fprintf(stderr, "server metadata failed\n");
    return 1;
  }
  tc::pb::ModelMetadataResponse model_md;
  if (!client->ModelMetadata(&model_md, "simple").IsOk() ||
      model_md.inputs_size() != 2) {
    fprintf(stderr, "model metadata failed\n");
    return 1;
  }
  tc::pb::ModelConfigResponse config;
  if (!client->ModelConfig(&config, "simple").IsOk() ||
      config.config().name() != "simple") {
    fprintf(stderr, "model config failed\n");
    return 1;
  }
  printf("PASS: grpc health metadata (server=%s)\n",
         server_md.name().c_str());
  return 0;
}
