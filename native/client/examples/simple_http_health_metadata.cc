// Health + metadata surface over HTTP (reference
// src/c++/examples/simple_http_health_metadata.cc behavior; HTTP metadata
// responses are JSON strings).

#include <cstdio>
#include <cstring>
#include <string>

#include "http_client.h"

namespace tc = tc_tpu::client;

int main(int argc, char** argv) {
  std::string url = "localhost:8000";
  for (int i = 1; i < argc - 1; ++i)
    if (strcmp(argv[i], "-u") == 0) url = argv[i + 1];

  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error err = tc::InferenceServerHttpClient::Create(&client, url);
  if (!err.IsOk()) {
    fprintf(stderr, "client creation failed: %s\n", err.Message().c_str());
    return 1;
  }
  bool live = false, ready = false, model_ready = false;
  if (!client->IsServerLive(&live).IsOk() || !live) {
    fprintf(stderr, "server not live\n");
    return 1;
  }
  if (!client->IsServerReady(&ready).IsOk() || !ready) {
    fprintf(stderr, "server not ready\n");
    return 1;
  }
  if (!client->IsModelReady(&model_ready, "simple").IsOk() || !model_ready) {
    fprintf(stderr, "model not ready\n");
    return 1;
  }
  std::string server_md, model_md, config, index;
  if (!client->ServerMetadata(&server_md).IsOk() ||
      server_md.find("extensions") == std::string::npos) {
    fprintf(stderr, "server metadata failed: %s\n", server_md.c_str());
    return 1;
  }
  if (!client->ModelMetadata(&model_md, "simple").IsOk() ||
      model_md.find("INPUT0") == std::string::npos) {
    fprintf(stderr, "model metadata failed\n");
    return 1;
  }
  // proto3 JSON omits zero-valued fields (simple has max_batch_size 0), so
  // key off the input list instead
  if (!client->ModelConfig(&config, "simple").IsOk() ||
      config.find("INPUT0") == std::string::npos) {
    fprintf(stderr, "model config failed\n");
    return 1;
  }
  if (!client->ModelRepositoryIndex(&index).IsOk() ||
      index.find("simple") == std::string::npos) {
    fprintf(stderr, "repository index failed\n");
    return 1;
  }
  printf("PASS: http health metadata\n");
  return 0;
}
