#include "h2.h"

#include <dlfcn.h>
#include <pthread.h>
#include <signal.h>
#include <sys/socket.h>

#include <algorithm>
#include <cstring>

#include "connio.h"
#include "sockio.h"

namespace tc_tpu {
namespace client {

namespace {

// ---- HTTP/2 constants (RFC 7540) ----
constexpr char kPreface[] = "PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n";
constexpr uint8_t kFrameData = 0x0;
constexpr uint8_t kFrameHeaders = 0x1;
constexpr uint8_t kFrameRstStream = 0x3;
constexpr uint8_t kFrameSettings = 0x4;
constexpr uint8_t kFramePing = 0x6;
constexpr uint8_t kFrameGoaway = 0x7;
constexpr uint8_t kFrameWindowUpdate = 0x8;
constexpr uint8_t kFrameContinuation = 0x9;
constexpr uint8_t kFlagEndStream = 0x1;
constexpr uint8_t kFlagAck = 0x1;
constexpr uint8_t kFlagEndHeaders = 0x4;
constexpr uint8_t kFlagPadded = 0x8;
constexpr uint8_t kFlagPriority = 0x20;
constexpr uint16_t kSettingsInitialWindowSize = 0x4;
constexpr uint16_t kSettingsMaxFrameSize = 0x5;
constexpr uint16_t kSettingsEnablePush = 0x2;
// our receive windows: large so responses stream without per-frame updates
constexpr long long kRecvWindow = 1 << 28;  // 256 MiB
constexpr long long kRecvReplenishAt = kRecvWindow / 2;

// ---- libnghttp2 HPACK inflater (stable C ABI, loaded at runtime) ----
struct NvABI {
  uint8_t* name;
  uint8_t* value;
  size_t namelen;
  size_t valuelen;
  uint8_t flags;
};
constexpr int kInflateFinal = 0x01;
constexpr int kInflateEmit = 0x02;

struct Hpack {
  int (*inflate_new)(void**) = nullptr;
  void (*inflate_del)(void*) = nullptr;
  long (*inflate_hd2)(void*, NvABI*, int*, const uint8_t*, size_t, int) =
      nullptr;
  int (*inflate_end_headers)(void*) = nullptr;
  bool ok = false;

  static const Hpack& Get() {
    static Hpack h = [] {
      Hpack out;
      void* lib = dlopen("libnghttp2.so.14", RTLD_NOW | RTLD_GLOBAL);
      if (lib == nullptr) lib = dlopen("libnghttp2.so", RTLD_NOW | RTLD_GLOBAL);
      if (lib == nullptr) return out;
      out.inflate_new = reinterpret_cast<int (*)(void**)>(
          dlsym(lib, "nghttp2_hd_inflate_new"));
      out.inflate_del = reinterpret_cast<void (*)(void*)>(
          dlsym(lib, "nghttp2_hd_inflate_del"));
      out.inflate_hd2 =
          reinterpret_cast<long (*)(void*, NvABI*, int*, const uint8_t*,
                                    size_t, int)>(
              dlsym(lib, "nghttp2_hd_inflate_hd2"));
      out.inflate_end_headers = reinterpret_cast<int (*)(void*)>(
          dlsym(lib, "nghttp2_hd_inflate_end_headers"));
      out.ok = out.inflate_new && out.inflate_del && out.inflate_hd2 &&
               out.inflate_end_headers;
      return out;
    }();
    return h;
  }
};

void PutU32(std::string* out, uint32_t v) {
  out->push_back(static_cast<char>((v >> 24) & 0xFF));
  out->push_back(static_cast<char>((v >> 16) & 0xFF));
  out->push_back(static_cast<char>((v >> 8) & 0xFF));
  out->push_back(static_cast<char>(v & 0xFF));
}

// HPACK literal-header-field-without-indexing encoder (RFC 7541 §6.2.2,
// no Huffman).  The decoder side needs full HPACK (the server compresses);
// the encoder side is allowed to never compress — same choice grpc-web
// made for its text framing.
void EncodeLiteral(std::string* out, const std::string& name,
                   const std::string& value) {
  auto put_len = [out](size_t n) {
    if (n < 0x7F) {
      out->push_back(static_cast<char>(n));
    } else {
      out->push_back(0x7F);
      size_t rem = n - 0x7F;
      while (rem >= 0x80) {
        out->push_back(static_cast<char>((rem & 0x7F) | 0x80));
        rem >>= 7;
      }
      out->push_back(static_cast<char>(rem));
    }
  };
  out->push_back(0x00);  // literal w/o indexing, new name
  put_len(name.size());
  out->append(name);
  put_len(value.size());
  out->append(value);
}

std::string LowerCopy(const std::string& s) {
  std::string out = s;
  std::transform(out.begin(), out.end(), out.begin(),
                 [](unsigned char c) { return std::tolower(c); });
  return out;
}

std::string PercentDecode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '%' && i + 2 < s.size()) {
      out.push_back(static_cast<char>(
          strtol(s.substr(i + 1, 2).c_str(), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(s[i]);
    }
  }
  return out;
}

// grpc-timeout header value: at most 8 digits (gRPC PROTOCOL-HTTP2 spec).
// Coarser units round UP so the server-side deadline is never shorter than
// the client's.
std::string GrpcTimeoutValue(uint64_t timeout_us) {
  constexpr uint64_t kMaxDigitsValue = 99999999;  // 8 digits
  if (timeout_us <= kMaxDigitsValue) return std::to_string(timeout_us) + "u";
  uint64_t ms = (timeout_us + 999) / 1000;
  if (ms <= kMaxDigitsValue) return std::to_string(ms) + "m";
  uint64_t s = (timeout_us + 999999) / 1000000;
  return std::to_string(std::min(s, kMaxDigitsValue)) + "S";
}

int ReadExactRetry(const connio::ConnRef& c, char* buf, size_t n,
                   const sockio::Deadline& dl) {
  // the EAGAIN retry must RESUME at the partial offset — restarting the
  // exact-read would overwrite bytes already consumed from the TLS stream
  // and desync the frame parser
  size_t got = 0;
  while (got < n) {
    ssize_t r = connio::CRecvDl(c, buf + got, n - got, dl);
    if (r == -2) return -2;
    if (r < 0 && !dl.enabled &&
        (errno == EAGAIN || errno == EWOULDBLOCK)) {
      continue;  // SO_RCVTIMEO tick on a TLS stream: yield, retry
    }
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 0;
}

Error IoError(int rc, const char* what) {
  if (rc == -2) {
    return Error(std::string("Deadline Exceeded: timed out ") + what);
  }
  return Error(std::string("connection failure while ") + what);
}

}  // namespace

bool H2Available() { return Hpack::Get().ok; }

H2GrpcConnection::~H2GrpcConnection() { Close(); }

void H2GrpcConnection::Close() {
  // the mux reader (if any) must exit before the TLS session it reads
  // from is freed: shutdown() wakes its blocked read, then join
  StopMux();
  if (tls_sess_ != nullptr) {
    delete tls_sess_;
    tls_sess_ = nullptr;
  }
  if (fd_ >= 0) {
    ::close(fd_);
    fd_ = -1;
  }
  if (inflater_ != nullptr) {
    Hpack::Get().inflate_del(inflater_);
    inflater_ = nullptr;
  }
  stream_active_ = false;
}

void H2GrpcConnection::StopMux() {
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    if (!mux_thread_.joinable()) return;
    if (!mux_dead_) {
      mux_dead_ = true;
      mux_err_ = Error("connection closed");
    }
  }
  if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);
  mux_cv_.notify_all();
  window_cv_.notify_all();
  mux_thread_.join();
}

Error H2GrpcConnection::Connect(
    const std::string& host, int port, bool* not_http2,
    int keepalive_idle_s, int keepalive_intvl_s, uint64_t timeout_us,
    const TlsContext* tls) {
  *not_http2 = false;
  if (!H2Available()) {
    return Error("HTTP/2 unavailable: libnghttp2 (HPACK decoder) not found");
  }
  Error err;
  auto dl = sockio::Deadline::In(timeout_us);
  fd_ = sockio::ConnectTcp(host, port, &err, dl);
  if (fd_ < 0) return err;
  sockio::EnableTcpKeepAlive(fd_, keepalive_idle_s, keepalive_intvl_s);
  if (tls != nullptr) {
    // real grpcs: TLS with ALPN "h2" — a peer negotiating anything else
    // (the HTTPS web bridge speaks http/1.1) is not an HTTP/2 endpoint
    tls_sess_ = new TlsSession();
    if (dl.enabled) {
      // the handshake must honor the connect deadline too (a peer that
      // accepts TCP then stalls in TLS would otherwise hang SSL_connect)
      long long rem = dl.RemainingUs();
      if (rem <= 0) {
        Close();
        return Error("Deadline Exceeded: timed out before TLS handshake");
      }
      sockio::SetSocketTimeout(fd_, SO_RCVTIMEO, rem);
      sockio::SetSocketTimeout(fd_, SO_SNDTIMEO, rem);
    }
    std::string selected;
    Error terr = tls_sess_->Handshake(fd_, *tls, host, "h2", &selected);
    if (dl.enabled) {
      // fresh connections may be pooled; don't leak this deadline
      sockio::SetSocketTimeout(fd_, SO_RCVTIMEO, 0);
      sockio::SetSocketTimeout(fd_, SO_SNDTIMEO, 0);
    }
    if (!terr.IsOk()) {
      Close();
      return terr;
    }
    if (selected != "h2") {
      Close();
      *not_http2 = true;
      return Error("server did not negotiate ALPN h2");
    }
  }

  // client preface + SETTINGS + connection WINDOW_UPDATE in one write
  std::string bytes(kPreface, sizeof(kPreface) - 1);
  std::string settings;
  auto put_setting = [&settings](uint16_t id, uint32_t v) {
    settings.push_back(static_cast<char>((id >> 8) & 0xFF));
    settings.push_back(static_cast<char>(id & 0xFF));
    PutU32(&settings, v);
  };
  put_setting(kSettingsEnablePush, 0);
  put_setting(kSettingsInitialWindowSize, kRecvWindow);
  bytes.push_back(0);  // frame: len(3) type flags sid(4)
  bytes.push_back(static_cast<char>((settings.size() >> 8) & 0xFF));
  bytes.push_back(static_cast<char>(settings.size() & 0xFF));
  bytes.push_back(static_cast<char>(kFrameSettings));
  bytes.push_back(0);
  PutU32(&bytes, 0);
  bytes.append(settings);
  // grow the connection-level recv window (it starts at 65535 regardless
  // of SETTINGS_INITIAL_WINDOW_SIZE)
  bytes.push_back(0);
  bytes.push_back(0);
  bytes.push_back(4);
  bytes.push_back(static_cast<char>(kFrameWindowUpdate));
  bytes.push_back(0);
  PutU32(&bytes, 0);
  PutU32(&bytes, static_cast<uint32_t>(kRecvWindow - 65535));
  const connio::ConnRef conn{fd_, tls_sess_};
  int rc = connio::CWriteAllDl(conn, bytes.data(), bytes.size(), dl);
  if (rc != 0) {
    Close();
    return IoError(rc, "sending HTTP/2 preface");
  }

  // first bytes back decide the protocol: an HTTP/1.1 server answers the
  // preface with "HTTP/1.1 4xx" text, a real h2c server with a SETTINGS
  // frame (type byte at offset 3)
  char probe[9];
  rc = ReadExactRetry(conn, probe, sizeof(probe), dl);
  if (rc != 0) {
    Close();
    return IoError(rc, "reading HTTP/2 settings");
  }
  if (std::memcmp(probe, "HTT", 3) == 0) {
    Close();
    *not_http2 = true;
    return Error("server is not HTTP/2");
  }
  if (probe[3] != static_cast<char>(kFrameSettings)) {
    Close();
    return Error("HTTP/2 handshake failed: first frame is not SETTINGS");
  }
  uint32_t len = (static_cast<uint8_t>(probe[0]) << 16) |
                 (static_cast<uint8_t>(probe[1]) << 8) |
                 static_cast<uint8_t>(probe[2]);
  std::string payload(len, '\0');
  if (len > 0) {
    rc = ReadExactRetry(conn, payload.data(), len, dl);
    if (rc != 0) {
      Close();
      return IoError(rc, "reading HTTP/2 settings");
    }
  }
  for (size_t off = 0; off + 6 <= payload.size(); off += 6) {
    uint16_t id = (static_cast<uint8_t>(payload[off]) << 8) |
                  static_cast<uint8_t>(payload[off + 1]);
    uint32_t v = (static_cast<uint8_t>(payload[off + 2]) << 24) |
                 (static_cast<uint8_t>(payload[off + 3]) << 16) |
                 (static_cast<uint8_t>(payload[off + 4]) << 8) |
                 static_cast<uint8_t>(payload[off + 5]);
    if (id == kSettingsInitialWindowSize) peer_initial_window_ = v;
    if (id == kSettingsMaxFrameSize) peer_max_frame_ = v;
  }
  TC_RETURN_IF_ERROR(SendFrame(kFrameSettings, kFlagAck, 0, ""));

  int irc = Hpack::Get().inflate_new(&inflater_);
  if (irc != 0) {
    Close();
    return Error("failed to create HPACK inflater");
  }
  return Error::Success;
}

Error H2GrpcConnection::SendFrame(
    uint8_t type, uint8_t flags, uint32_t stream_id,
    const std::string& payload) {
  std::string hdr;
  hdr.reserve(9 + payload.size());
  hdr.push_back(static_cast<char>((payload.size() >> 16) & 0xFF));
  hdr.push_back(static_cast<char>((payload.size() >> 8) & 0xFF));
  hdr.push_back(static_cast<char>(payload.size() & 0xFF));
  hdr.push_back(static_cast<char>(type));
  hdr.push_back(static_cast<char>(flags));
  PutU32(&hdr, stream_id & 0x7FFFFFFF);
  hdr.append(payload);
  std::lock_guard<std::mutex> lk(write_mu_);
  if (fd_ < 0) return Error("connection closed");
  if (!connio::CWriteAll(connio::ConnRef{fd_, tls_sess_}, hdr.data(),
                         hdr.size())) {
    Error err("connection failure while sending HTTP/2 frame");
    {
      // a failed (possibly partial) write leaves the byte stream mid-frame
      // — in mux mode every other caller shares it, so the channel must
      // die NOW, not when the reader eventually notices
      std::lock_guard<std::mutex> slk(state_mu_);
      if (mux_on_ && !mux_dead_) {
        mux_dead_ = true;
        mux_err_ = err;
      }
    }
    if (mux_on_) {
      if (fd_ >= 0) ::shutdown(fd_, SHUT_RDWR);  // wake the reader
      mux_cv_.notify_all();
      window_cv_.notify_all();
    }
    return err;
  }
  return Error::Success;
}

Error H2GrpcConnection::ReadFrameHdr(FrameHdr* hdr,
                                     const sockio::Deadline& dl) {
  char raw[9];
  int rc = ReadExactRetry(connio::ConnRef{fd_, tls_sess_}, raw,
                          sizeof(raw), dl);
  if (rc != 0) return IoError(rc, "reading HTTP/2 frame");
  hdr->len = (static_cast<uint8_t>(raw[0]) << 16) |
             (static_cast<uint8_t>(raw[1]) << 8) |
             static_cast<uint8_t>(raw[2]);
  hdr->type = static_cast<uint8_t>(raw[3]);
  hdr->flags = static_cast<uint8_t>(raw[4]);
  hdr->stream_id = ((static_cast<uint8_t>(raw[5]) & 0x7F) << 24) |
                   (static_cast<uint8_t>(raw[6]) << 16) |
                   (static_cast<uint8_t>(raw[7]) << 8) |
                   static_cast<uint8_t>(raw[8]);
  return Error::Success;
}

Error H2GrpcConnection::InflateHeaderBlock(const std::string& block,
                                           Headers* out) {
  const Hpack& hp = Hpack::Get();
  const uint8_t* in = reinterpret_cast<const uint8_t*>(block.data());
  size_t inlen = block.size();
  // nghttp2 contract: keep calling (even with no input left) until the
  // FINAL flag; EMIT may arrive on calls that consume zero bytes
  for (;;) {
    NvABI nv;
    int flags = 0;
    long rv = hp.inflate_hd2(inflater_, &nv, &flags, in, inlen, 1);
    if (rv < 0) {
      return Error("HPACK decoding failed (error " + std::to_string(rv) +
                   ")");
    }
    in += rv;
    inlen -= static_cast<size_t>(rv);
    if (flags & kInflateEmit) {
      std::string name(reinterpret_cast<char*>(nv.name), nv.namelen);
      std::string value(reinterpret_cast<char*>(nv.value), nv.valuelen);
      // repeated headers (rare here) keep the last value — fine for our use
      (*out)[LowerCopy(name)] = value;
    }
    if (flags & kInflateFinal) {
      hp.inflate_end_headers(inflater_);
      return Error::Success;
    }
    if (inlen == 0 && !(flags & kInflateEmit)) {
      // no progress possible: the block ended mid-entry
      return Error("HPACK decoding failed: truncated header block");
    }
  }
}

Error H2GrpcConnection::ReplenishRecvWindow(uint32_t stream_id,
                                            size_t consumed) {
  conn_recv_consumed_ += static_cast<long long>(consumed);
  if (conn_recv_consumed_ < kRecvReplenishAt) return Error::Success;
  std::string upd;
  PutU32(&upd, static_cast<uint32_t>(conn_recv_consumed_));
  Error err = SendFrame(kFrameWindowUpdate, 0, 0, upd);
  if (err.IsOk() && stream_id != 0) {
    err = SendFrame(kFrameWindowUpdate, 0, stream_id, upd);
  }
  conn_recv_consumed_ = 0;
  return err;
}

// Which call a frame for `id` belongs to: the caller-driven call (`cur`,
// unary/bidi) or a registered mux call.  `*pin` keeps a mux call alive
// while this frame mutates it, even if its caller unregisters (deadline)
// concurrently.
H2GrpcConnection::CallState* H2GrpcConnection::TargetFor(
    uint32_t id, CallState* cur, std::shared_ptr<CallState>* pin) {
  if (cur != nullptr && id == cur->stream_id) return cur;
  std::lock_guard<std::mutex> lk(state_mu_);
  auto it = mux_calls_.find(id);
  if (it == mux_calls_.end()) return nullptr;
  *pin = it->second;
  return pin->get();
}

// Read + dispatch exactly one frame.  `call` is the caller-driven RPC when
// one runs this connection (pooled unary, bidi stream); nullptr in mux mode
// where the reader thread dispatches per stream id.  Connection-level
// frames update windows/settings either way.
Error H2GrpcConnection::ProcessOneFrame(CallState* call,
                                        const sockio::Deadline& dl) {
  FrameHdr hdr;
  TC_RETURN_IF_ERROR(ReadFrameHdr(&hdr, dl));
  std::string payload(hdr.len, '\0');
  if (hdr.len > 0) {
    int rc = ReadExactRetry(connio::ConnRef{fd_, tls_sess_},
                            payload.data(), hdr.len, dl);
    if (rc != 0) return IoError(rc, "reading HTTP/2 frame payload");
  }
  std::shared_ptr<CallState> pin;
  switch (hdr.type) {
    case kFrameData: {
      size_t off = 0, len = payload.size();
      if (hdr.flags & kFlagPadded) {
        if (payload.empty()) return Error("malformed padded DATA frame");
        uint8_t pad = static_cast<uint8_t>(payload[0]);
        if (1u + pad > payload.size()) {
          return Error("malformed padded DATA frame");
        }
        off = 1;
        len = payload.size() - 1 - pad;
      }
      CallState* t = TargetFor(hdr.stream_id, call, &pin);
      if (t != nullptr) {
        t->data.append(payload, off, len);
        if (max_response_bytes_ > 0 &&
            t->data.size() > max_response_bytes_ + 5) {
          // enforced mid-read: the cap must bound memory, not just be
          // checked after the whole body buffered (connection-fatal: the
          // peer is mid-stream and the HPACK/frame state can't be resynced)
          return Error(
              "response exceeds maximum receive message size of " +
              std::to_string(max_response_bytes_) + " bytes");
        }
        if (hdr.flags & kFlagEndStream) {
          std::lock_guard<std::mutex> lk(state_mu_);
          t->end_stream = true;
        }
      }
      // count the whole frame against our recv window (padding included);
      // no stream-level update for a stream that just ended or one we no
      // longer track (RFC 7540 §5.1 closed-state)
      bool stream_open = t != nullptr && !(hdr.flags & kFlagEndStream);
      TC_RETURN_IF_ERROR(ReplenishRecvWindow(
          stream_open ? hdr.stream_id : 0, payload.size()));
      break;
    }
    case kFrameHeaders: {
      size_t off = 0, len = payload.size();
      if (hdr.flags & kFlagPadded) {
        if (payload.empty()) return Error("malformed padded HEADERS frame");
        uint8_t pad = static_cast<uint8_t>(payload[0]);
        off = 1;
        if (1u + pad > payload.size()) {
          return Error("malformed padded HEADERS frame");
        }
        len = payload.size() - 1 - pad;
      }
      if (hdr.flags & kFlagPriority) {
        if (len < 5) return Error("malformed HEADERS frame");
        off += 5;
        len -= 5;
      }
      CallState* t = TargetFor(hdr.stream_id, call, &pin);
      if (t != nullptr) {
        t->header_block.append(payload, off, len);
        if (hdr.flags & kFlagEndStream) t->end_after_headers = true;
        if (hdr.flags & kFlagEndHeaders) {
          TC_RETURN_IF_ERROR(
              InflateHeaderBlock(t->header_block, &t->headers));
          t->header_block.clear();
          t->headers_done = true;
          if (t->end_after_headers) {
            std::lock_guard<std::mutex> lk(state_mu_);
            t->end_stream = true;
          }
        }
      } else {
        // a header block we are not tracking still goes through the
        // inflater (HPACK state is connection-wide)
        Headers ignored;
        TC_RETURN_IF_ERROR(InflateHeaderBlock(
            payload.substr(off, len), &ignored));
      }
      break;
    }
    case kFrameContinuation: {
      CallState* t = TargetFor(hdr.stream_id, call, &pin);
      if (t != nullptr) {
        t->header_block.append(payload);
        if (hdr.flags & kFlagEndHeaders) {
          TC_RETURN_IF_ERROR(
              InflateHeaderBlock(t->header_block, &t->headers));
          t->header_block.clear();
          t->headers_done = true;
          if (t->end_after_headers) {
            std::lock_guard<std::mutex> lk(state_mu_);
            t->end_stream = true;
          }
        }
      }
      break;
    }
    case kFrameSettings: {
      if (hdr.flags & kFlagAck) break;
      for (size_t off = 0; off + 6 <= payload.size(); off += 6) {
        uint16_t id = (static_cast<uint8_t>(payload[off]) << 8) |
                      static_cast<uint8_t>(payload[off + 1]);
        uint32_t v = (static_cast<uint8_t>(payload[off + 2]) << 24) |
                     (static_cast<uint8_t>(payload[off + 3]) << 16) |
                     (static_cast<uint8_t>(payload[off + 4]) << 8) |
                     static_cast<uint8_t>(payload[off + 5]);
        std::lock_guard<std::mutex> lk(state_mu_);
        if (id == kSettingsInitialWindowSize) {
          // adjust every open stream's window by the delta (RFC 7540
          // §6.9.2): the caller-driven call, the bidi stream, and all
          // registered mux calls
          long long delta =
              static_cast<long long>(v) - peer_initial_window_;
          if (call != nullptr) call->send_window += delta;
          if (stream_active_ && call != &stream_call_) {
            stream_call_.send_window += delta;
          }
          for (auto& kv : mux_calls_) kv.second->send_window += delta;
          peer_initial_window_ = v;
        }
        if (id == kSettingsMaxFrameSize) peer_max_frame_ = v;
      }
      window_cv_.notify_all();
      TC_RETURN_IF_ERROR(SendFrame(kFrameSettings, kFlagAck, 0, ""));
      break;
    }
    case kFramePing: {
      if (!(hdr.flags & kFlagAck)) {
        TC_RETURN_IF_ERROR(SendFrame(kFramePing, kFlagAck, 0, payload));
      }
      break;
    }
    case kFrameWindowUpdate: {
      if (payload.size() < 4) return Error("malformed WINDOW_UPDATE");
      uint32_t inc = ((static_cast<uint8_t>(payload[0]) & 0x7F) << 24) |
                     (static_cast<uint8_t>(payload[1]) << 16) |
                     (static_cast<uint8_t>(payload[2]) << 8) |
                     static_cast<uint8_t>(payload[3]);
      if (hdr.stream_id == 0) {
        std::lock_guard<std::mutex> lk(state_mu_);
        conn_send_window_ += inc;
      } else {
        CallState* t = TargetFor(hdr.stream_id, call, &pin);
        if (t != nullptr) {
          std::lock_guard<std::mutex> lk(state_mu_);
          t->send_window += inc;
        }
      }
      window_cv_.notify_all();
      break;
    }
    case kFrameRstStream: {
      CallState* t = TargetFor(hdr.stream_id, call, &pin);
      if (t != nullptr) {
        if (payload.size() >= 4) {
          t->reset_code = (static_cast<uint8_t>(payload[0]) << 24) |
                          (static_cast<uint8_t>(payload[1]) << 16) |
                          (static_cast<uint8_t>(payload[2]) << 8) |
                          static_cast<uint8_t>(payload[3]);
        }
        std::lock_guard<std::mutex> lk(state_mu_);
        t->reset = true;
        t->end_stream = true;
      }
      break;
    }
    case kFrameGoaway: {
      uint32_t code = 0;
      if (payload.size() >= 8) {
        code = (static_cast<uint8_t>(payload[4]) << 24) |
               (static_cast<uint8_t>(payload[5]) << 16) |
               (static_cast<uint8_t>(payload[6]) << 8) |
               static_cast<uint8_t>(payload[7]);
      }
      return Error("server sent GOAWAY (error code " + std::to_string(code) +
                   ")");
    }
    default:
      break;  // PRIORITY / PUSH_PROMISE(disabled) / unknown: ignore
  }
  return Error::Success;
}

Error H2GrpcConnection::SendHeaders(
    const std::string& path, const Headers& metadata, uint32_t stream_id,
    uint64_t timeout_us, bool end_stream) {
  std::string block;
  EncodeLiteral(&block, ":method", "POST");
  EncodeLiteral(&block, ":scheme",
                tls_sess_ != nullptr ? "https" : "http");
  EncodeLiteral(&block, ":path", path);
  EncodeLiteral(&block, ":authority", "localhost");
  EncodeLiteral(&block, "te", "trailers");
  EncodeLiteral(&block, "content-type", "application/grpc");
  if (timeout_us > 0) {
    EncodeLiteral(&block, "grpc-timeout", GrpcTimeoutValue(timeout_us));
  }
  for (const auto& kv : metadata) {
    std::string name = LowerCopy(kv.first);
    if (name == "content-type" || name == "te" || name[0] == ':') continue;
    EncodeLiteral(&block, name, kv.second);
  }
  uint8_t flags = kFlagEndHeaders;
  if (end_stream) flags |= kFlagEndStream;
  return SendFrame(kFrameHeaders, flags, stream_id, block);
}

// gRPC message framing + DATA flow control: chunk to the peer's max frame
// size and block on the send windows.  `call` is the REAL call state —
// frames consumed while blocked (unary path) land in it, so an early
// server response (RST / trailers-only rejection before the full body) is
// never lost.  On the bidi stream the reader thread consumes frames; the
// writer waits on the window condvar and also wakes when the stream dies.
Error H2GrpcConnection::SendGrpcMessage(
    const std::string& message, CallState* call, bool end_stream,
    const sockio::Deadline& dl) {
  std::string framed;
  framed.reserve(5 + message.size());
  framed.push_back(0);  // uncompressed
  PutU32(&framed, static_cast<uint32_t>(message.size()));
  framed.append(message);

  size_t off = 0;
  while (off < framed.size()) {
    long long budget;
    bool reader_active;
    size_t chunk = 0;
    {
      std::unique_lock<std::mutex> lk(state_mu_);
      budget = std::min(conn_send_window_, call->send_window);
      // a background thread (bidi reader or mux reader) consumes frames —
      // writers park on the window condvar instead of self-reading
      reader_active = stream_active_ || mux_on_;
      if (budget > 0) {
        // RESERVE the chunk under the lock: concurrent mux writers that
        // each read the budget and debit after sending would jointly
        // overshoot the connection window (FLOW_CONTROL_ERROR -> GOAWAY)
        chunk = std::min(
            {framed.size() - off, static_cast<size_t>(budget),
             static_cast<size_t>(peer_max_frame_)});
        conn_send_window_ -= static_cast<long long>(chunk);
        call->send_window -= static_cast<long long>(chunk);
      }
      if (budget <= 0 && reader_active) {
        // the reader thread consumes WINDOW_UPDATEs; wait here — and also
        // wake when the call/connection dies, or we deadlock forever on a
        // window that will never be replenished
        auto woke = [this, call] {
          return std::min(conn_send_window_, call->send_window) > 0 ||
                 call->end_stream || call->reset || mux_dead_ ||
                 (!stream_active_ && !mux_on_);
        };
        bool ok = true;
        if (dl.enabled) {
          long long rem = dl.RemainingUs();
          if (rem <= 0) return Error("Deadline Exceeded: send window");
          ok = window_cv_.wait_for(lk, std::chrono::microseconds(rem),
                                   woke);
        } else {
          window_cv_.wait(lk, woke);
        }
        if (!ok) return Error("Deadline Exceeded: send window");
        if (mux_dead_) return mux_err_;
        if (call->end_stream || call->reset) {
          // server closed the stream early (e.g. rejected mid-upload):
          // stop sending, let the caller read the status
          return Error::Success;
        }
        if (!stream_active_ && !mux_on_) {
          return Error("stream closed while awaiting send window");
        }
        continue;
      }
    }
    if (!reader_active && (call->end_stream || call->reset)) {
      // pooled unary path (single-threaded, no race on `call`): the
      // server already closed the stream — e.g. rejected the request
      // mid-upload — so stop sending and let the caller read the status
      if (chunk > 0) {
        // refund the reserved-but-unsent budget: this connection may be
        // pooled and reused, and a phantom debit never gets replenished
        std::lock_guard<std::mutex> lk(state_mu_);
        conn_send_window_ += static_cast<long long>(chunk);
        call->send_window += static_cast<long long>(chunk);
      }
      return Error::Success;
    }
    if (budget <= 0) {
      // pooled unary path: nobody else reads — consume frames (into the
      // real call state) until the peer replenishes a window
      TC_RETURN_IF_ERROR(ProcessOneFrame(call, dl));
      continue;
    }
    bool last = (off + chunk == framed.size());
    TC_RETURN_IF_ERROR(SendFrame(
        kFrameData, (last && end_stream) ? kFlagEndStream : 0,
        call->stream_id, framed.substr(off, chunk)));
    off += chunk;
  }
  return Error::Success;
}

Error H2GrpcConnection::GrpcStatusToError(const Headers& h) {
  auto st = h.find("grpc-status");
  if (st == h.end()) {
    auto status = h.find(":status");
    if (status != h.end() && status->second != "200") {
      return Error("rpc failed with HTTP status " + status->second);
    }
    return Error("response missing grpc-status");
  }
  int code = atoi(st->second.c_str());
  if (code == 0) return Error::Success;
  auto msg = h.find("grpc-message");
  std::string text =
      msg != h.end() ? PercentDecode(msg->second) : std::string();
  if (code == 4 && text.empty()) text = "Deadline Exceeded";
  return Error(text.empty()
                   ? "rpc failed with status " + std::to_string(code)
                   : text);
}

Error H2GrpcConnection::UnaryCall(
    const std::string& path, const std::string& request,
    const Headers& metadata, std::string* response, uint64_t timeout_us,
    RequestTimers* timers) {
  if (fd_ < 0) return Error("connection closed");
  if (stream_active_) {
    return Error("connection is running a stream");
  }
  if (mux_on_) {
    return Error("connection is multiplexed; use MuxUnaryCall");
  }
  auto dl = sockio::Deadline::In(timeout_us);
  CallState call;
  call.stream_id = next_stream_id_;
  next_stream_id_ += 2;
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    call.send_window = peer_initial_window_;
  }
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::SEND_START);
  }
  Error err = SendHeaders(path, metadata, call.stream_id, timeout_us, false);
  if (err.IsOk()) err = SendGrpcMessage(request, &call, true, dl);
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::SEND_END);
    timers->CaptureTimestamp(RequestTimers::Kind::RECV_START);
  }
  while (err.IsOk() && !call.end_stream) {
    err = ProcessOneFrame(&call, dl);
  }
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::RECV_END);
  }
  if (!err.IsOk()) {
    // the connection state (HPACK tables, half-open stream) is now
    // indeterminate — this connection must not be reused
    Close();
    return err;
  }
  if (call.reset) {
    Close();
    return Error("rpc aborted: RST_STREAM (error code " +
                 std::to_string(call.reset_code) + ")");
  }
  TC_RETURN_IF_ERROR(GrpcStatusToError(call.headers));
  if (call.data.size() < 5) {
    return Error("rpc returned no response message");
  }
  uint32_t len = (static_cast<uint8_t>(call.data[1]) << 24) |
                 (static_cast<uint8_t>(call.data[2]) << 16) |
                 (static_cast<uint8_t>(call.data[3]) << 8) |
                 static_cast<uint8_t>(call.data[4]);
  if (call.data.size() < 5u + len) {
    return Error("truncated gRPC response message");
  }
  response->assign(call.data, 5, len);
  return Error::Success;
}

Error H2GrpcConnection::StartStream(const std::string& path,
                                    const Headers& metadata) {
  if (fd_ < 0) return Error("connection closed");
  if (stream_active_) return Error("stream already running");
  if (mux_on_) return Error("connection is multiplexed");
  if (tls_sess_ != nullptr) {
    // reader thread and writer share one TLS session (internally mutexed);
    // a short receive timeout makes the blocked reader release the session
    // periodically so writes get through (same pattern as the TLS duplex
    // web stream in transport.cc)
    sockio::SetSocketTimeout(fd_, SO_RCVTIMEO, 50000);
  }
  stream_call_ = CallState();
  stream_call_.stream_id = next_stream_id_;
  next_stream_id_ += 2;
  stream_read_pos_ = 0;
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    stream_call_.send_window = peer_initial_window_;
    stream_active_ = true;
  }
  return SendHeaders(path, metadata, stream_call_.stream_id, 0, false);
}

Error H2GrpcConnection::StreamWrite(const std::string& message) {
  if (!stream_active_) return Error("no active stream");
  return SendGrpcMessage(message, &stream_call_, false, sockio::Deadline());
}

Error H2GrpcConnection::StreamWritesDone() {
  if (!stream_active_) return Error("no active stream");
  return SendFrame(kFrameData, kFlagEndStream, stream_call_.stream_id, "");
}

Error H2GrpcConnection::StreamRead(std::string* message, bool* done) {
  *done = false;
  sockio::Deadline dl;  // streams live until closed
  for (;;) {
    // a complete message already buffered?
    if (stream_call_.data.size() >= stream_read_pos_ + 5) {
      const std::string& d = stream_call_.data;
      size_t p = stream_read_pos_;
      uint32_t len = (static_cast<uint8_t>(d[p + 1]) << 24) |
                     (static_cast<uint8_t>(d[p + 2]) << 16) |
                     (static_cast<uint8_t>(d[p + 3]) << 8) |
                     static_cast<uint8_t>(d[p + 4]);
      if (d.size() >= p + 5u + len) {
        message->assign(d, p + 5, len);
        stream_read_pos_ = p + 5 + len;
        if (stream_read_pos_ == d.size()) {
          stream_call_.data.clear();
          stream_read_pos_ = 0;
        }
        return Error::Success;
      }
    }
    if (stream_call_.end_stream) {
      *done = true;
      {
        std::lock_guard<std::mutex> lk(state_mu_);
        stream_active_ = false;
      }
      window_cv_.notify_all();
      if (stream_call_.reset) {
        return Error("stream aborted: RST_STREAM (error code " +
                     std::to_string(stream_call_.reset_code) + ")");
      }
      return GrpcStatusToError(stream_call_.headers);
    }
    Error err = ProcessOneFrame(&stream_call_, dl);
    if (!err.IsOk()) {
      *done = true;
      {
        std::lock_guard<std::mutex> lk(state_mu_);
        stream_active_ = false;
      }
      window_cv_.notify_all();
      return err;
    }
  }
}

// ---- multiplexed unary mode ------------------------------------------

Error H2GrpcConnection::StartMux() {
  if (fd_ < 0) return Error("connection closed");
  if (stream_active_) return Error("connection is running a stream");
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    if (mux_on_) return Error::Success;
    mux_on_ = true;
  }
  if (tls_sess_ != nullptr) {
    // reader thread and N writers share one TLS session (internally
    // mutexed); a short receive timeout makes the blocked reader release
    // the session periodically so writes get through (same pattern as the
    // bidi stream)
    sockio::SetSocketTimeout(fd_, SO_RCVTIMEO, 50000);
  }
  mux_thread_ = std::thread([this] { MuxReaderLoop(); });
  return Error::Success;
}

bool H2GrpcConnection::MuxHealthy() {
  std::lock_guard<std::mutex> lk(state_mu_);
  return mux_on_ && !mux_dead_ && fd_ >= 0;
}

void H2GrpcConnection::MuxReaderLoop() {
  // block SIGPIPE for this thread's lifetime: the per-operation TLS guard
  // then short-circuits (mask already blocked), so the hot per-frame read
  // path doesn't pay mask-juggling syscalls
  sigset_t pipe_set;
  sigemptyset(&pipe_set);
  sigaddset(&pipe_set, SIGPIPE);
  pthread_sigmask(SIG_BLOCK, &pipe_set, nullptr);
  for (;;) {
    Error err = ProcessOneFrame(nullptr, sockio::Deadline());
    {
      // the lock release below publishes this frame's CallState writes to
      // callers woken by the notify (they re-check under state_mu_)
      std::lock_guard<std::mutex> lk(state_mu_);
      if (!err.IsOk()) {
        if (!mux_dead_) {
          mux_dead_ = true;
          mux_err_ = err;
        }
      } else if (mux_dead_) {
        err = mux_err_;  // StopMux raced in: exit
      }
    }
    mux_cv_.notify_all();
    window_cv_.notify_all();
    if (!err.IsOk()) return;
  }
}

Error H2GrpcConnection::MuxUnaryCall(
    const std::string& path, const std::string& request,
    const Headers& metadata, std::string* response, uint64_t timeout_us,
    RequestTimers* timers) {
  auto dl = sockio::Deadline::In(timeout_us);
  auto call = std::make_shared<CallState>();
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    if (!mux_on_) return Error("connection is not multiplexed");
    if (mux_dead_) return mux_err_;
    call->send_window = peer_initial_window_;
  }
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::SEND_START);
  }
  Error err;
  {
    // stream ids must hit the wire in allocation order (RFC 7540 §5.1.1:
    // HEADERS for id N implicitly closes idle streams below N), so the id
    // grab and the HEADERS frame go out under one lock
    std::lock_guard<std::mutex> open(open_mu_);
    {
      std::lock_guard<std::mutex> lk(state_mu_);
      call->stream_id = next_stream_id_;
      next_stream_id_ += 2;
      mux_calls_[call->stream_id] = call;
    }
    err = SendHeaders(path, metadata, call->stream_id, timeout_us, false);
  }
  if (err.IsOk()) err = SendGrpcMessage(request, call.get(), true, dl);
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::SEND_END);
    timers->CaptureTimestamp(RequestTimers::Kind::RECV_START);
  }
  if (err.IsOk()) {
    std::unique_lock<std::mutex> lk(state_mu_);
    auto done = [this, &call] {
      return call->end_stream || call->reset || mux_dead_;
    };
    if (dl.enabled) {
      long long rem = dl.RemainingUs();
      if (rem <= 0 ||
          !mux_cv_.wait_for(lk, std::chrono::microseconds(rem), done)) {
        err = Error("Deadline Exceeded");
      }
    } else {
      mux_cv_.wait(lk, done);
    }
    if (err.IsOk() && mux_dead_ && !call->end_stream && !call->reset) {
      err = mux_err_;
    }
  }
  bool conn_alive, call_done;
  {
    std::lock_guard<std::mutex> lk(state_mu_);
    mux_calls_.erase(call->stream_id);
    conn_alive = !mux_dead_ && fd_ >= 0;
    call_done = call->end_stream || call->reset;
  }
  if (timers != nullptr) {
    timers->CaptureTimestamp(RequestTimers::Kind::RECV_END);
  }
  if (!err.IsOk()) {
    if (conn_alive && !call_done) {
      // deadline expired with the stream still open: cancel it so the
      // server stops and the connection stays clean for other calls
      std::string code;
      PutU32(&code, 8);  // CANCEL
      SendFrame(kFrameRstStream, 0, call->stream_id, code);
    }
    return err;
  }
  if (call->reset) {
    return Error("rpc aborted: RST_STREAM (error code " +
                 std::to_string(call->reset_code) + ")");
  }
  TC_RETURN_IF_ERROR(GrpcStatusToError(call->headers));
  if (call->data.size() < 5) {
    return Error("rpc returned no response message");
  }
  uint32_t len = (static_cast<uint8_t>(call->data[1]) << 24) |
                 (static_cast<uint8_t>(call->data[2]) << 16) |
                 (static_cast<uint8_t>(call->data[3]) << 8) |
                 static_cast<uint8_t>(call->data[4]);
  if (call->data.size() < 5u + len) {
    return Error("truncated gRPC response message");
  }
  response->assign(call->data, 5, len);
  return Error::Success;
}

}  // namespace client
}  // namespace tc_tpu
