// TLS-aware connection IO: dispatch to a TlsSession when present, else the
// plain sockio helpers.  Shared by the HTTP/1.1 transport (transport.cc)
// and the HTTP/2 gRPC layer (h2.cc).  Deadline semantics match sockio
// (-2 = expired).
#pragma once

#include <cerrno>
#include <cstddef>

#include "sockio.h"
#include "tls.h"

namespace tc_tpu {
namespace client {
namespace connio {

struct ConnRef {
  int fd;
  TlsSession* tls;
};

inline ssize_t CRecvDl(const ConnRef& c, char* buf, size_t n,
                       const sockio::Deadline& dl) {
  if (c.tls == nullptr) return sockio::RecvDl(c.fd, buf, n, dl);
  if (dl.enabled) {
    long long rem = dl.RemainingUs();
    if (rem <= 0) return -2;
    sockio::SetSocketTimeout(c.fd, SO_RCVTIMEO, rem);
  }
  long r = c.tls->Recv(buf, n);
  if (r < 0 && dl.enabled && (errno == EAGAIN || errno == EWOULDBLOCK)) {
    return -2;
  }
  return r;
}

inline int CReadExactDl(const ConnRef& c, char* buf, size_t n,
                        const sockio::Deadline& dl) {
  if (c.tls == nullptr) return sockio::ReadExactDl(c.fd, buf, n, dl);
  size_t got = 0;
  while (got < n) {
    ssize_t r = CRecvDl(c, buf + got, n - got, dl);
    if (r == -2) return -2;
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 0;
}

inline int CWriteAllDl(const ConnRef& c, const char* buf, size_t n,
                       const sockio::Deadline& dl) {
  if (c.tls == nullptr) return sockio::WriteAllDl(c.fd, buf, n, dl);
  size_t sent = 0;
  while (sent < n) {
    if (dl.enabled) {
      long long rem = dl.RemainingUs();
      if (rem <= 0) return -2;
      sockio::SetSocketTimeout(c.fd, SO_SNDTIMEO, rem);
    }
    long w = c.tls->Send(buf + sent, n - sent);
    if (w <= 0) {
      if (dl.enabled && (errno == EAGAIN || errno == EWOULDBLOCK)) return -2;
      return -1;
    }
    sent += static_cast<size_t>(w);
  }
  return 0;
}

inline bool CWriteAll(const ConnRef& c, const char* buf, size_t n) {
  return CWriteAllDl(c, buf, n, sockio::Deadline()) == 0;
}

}  // namespace connio
}  // namespace client
}  // namespace tc_tpu
