// gRPC over cleartext HTTP/2 (h2c, prior knowledge) — the wire the stock
// gRPC port speaks.
//
// Parity target: the reference C++ client is grpc++ over HTTP/2
// (/root/reference/src/c++/library/grpc_client.cc:1093-1150 sync RPC,
// :1628-1673 bidi streams).  The image ships no grpc++ headers, so this
// implements the protocol directly: own HTTP/2 framing (RFC 7540 — frame
// layer, SETTINGS/PING/WINDOW_UPDATE handling, flow-control windows both
// directions) plus HPACK (RFC 7541) with a literal-without-indexing encoder
// and the system libnghttp2's inflater (dlopen'd; handles Huffman + the
// server's dynamic table) for decoding.
//
// Concurrency model: two modes.
//  * Pooled (default fallback): ONE in-flight RPC per connection; the
//    client pools connections for concurrent unary calls.
//  * Multiplexed (StartMux): a dedicated reader thread dispatches frames
//    to concurrent unary calls by stream id, so N callers share ONE
//    socket — grpc++-style channel multiplexing (reference
//    grpc_client.cc:47-152).
// The bidi stream runs reads and writes concurrently on its dedicated
// connection in either mode.
#pragma once

#include <condition_variable>
#include <cstdint>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "tls.h"

namespace tc_tpu {
namespace client {

namespace sockio {
struct Deadline;  // sockio.h (header-only)
}

using Headers = std::map<std::string, std::string>;

// True when the HPACK decoder (libnghttp2) is loadable — h2c mode needs it.
bool H2Available();

class H2GrpcConnection {
 public:
  H2GrpcConnection() = default;
  ~H2GrpcConnection();

  H2GrpcConnection(const H2GrpcConnection&) = delete;
  H2GrpcConnection& operator=(const H2GrpcConnection&) = delete;

  // TCP connect + HTTP/2 preface/SETTINGS exchange.  Fails fast with
  // `not_http2` set (and no Error) when the peer answered the preface with
  // HTTP/1.1 text — the caller falls back to the gRPC-Web bridge.
  // `tls` non-null wraps the connection in TLS with ALPN "h2" (real grpcs);
  // a peer that negotiates anything else sets `not_http2` so the caller
  // falls back to gRPC-Web over TLS.
  Error Connect(
      const std::string& host, int port, bool* not_http2,
      int keepalive_idle_s = 0, int keepalive_intvl_s = 0,
      uint64_t timeout_us = 0, const TlsContext* tls = nullptr);
  bool connected() const { return fd_ >= 0; }

  // Abort DATA accumulation past this many bytes (reference
  // GRPC_ARG_MAX_RECEIVE_MESSAGE_LENGTH — enforced mid-read so the cap
  // actually bounds memory); 0 = unlimited.
  void SetMaxResponseBytes(size_t max_bytes) {
    max_response_bytes_ = max_bytes;
  }

  // One unary RPC: serialized request pb in, serialized response pb out.
  // A non-zero grpc-status comes back as an Error carrying the server's
  // grpc-message.  `timeout_us` is both the socket deadline and the
  // `grpc-timeout` header (server-side deadline propagation).  `timers`
  // (optional) gets SEND_START/SEND_END/RECV_START/RECV_END stamps.
  Error UnaryCall(
      const std::string& path, const std::string& request,
      const Headers& metadata, std::string* response,
      uint64_t timeout_us = 0, RequestTimers* timers = nullptr);

  // ---- multiplexed unary mode ----
  // Spawn the reader thread: afterwards MuxUnaryCall may be invoked from
  // any number of threads concurrently; frames are dispatched to calls by
  // stream id.  Mutually exclusive with UnaryCall/StartStream on this
  // connection.
  Error StartMux();
  // False once the connection died (reader exited); pending calls fail
  // with the fatal error and the owner should replace the channel.
  bool MuxHealthy();
  Error MuxUnaryCall(
      const std::string& path, const std::string& request,
      const Headers& metadata, std::string* response,
      uint64_t timeout_us = 0, RequestTimers* timers = nullptr);

  // ---- bidi stream (single stream per connection) ----
  Error StartStream(const std::string& path, const Headers& metadata);
  // Send one gRPC message (length-prefixed DATA). Thread-safe vs reads.
  Error StreamWrite(const std::string& message);
  // Half-close (END_STREAM on an empty DATA frame).
  Error StreamWritesDone();
  // Next response message; *done=true once the server closed the stream
  // (the returned Error is then the final grpc-status).  Call from a single
  // reader thread.
  Error StreamRead(std::string* message, bool* done);

  void Close();

 private:
  struct FrameHdr {
    uint32_t len = 0;
    uint8_t type = 0;
    uint8_t flags = 0;
    uint32_t stream_id = 0;
  };
  struct CallState {
    uint32_t stream_id = 0;
    Headers headers;          // response headers + trailers, merged
    std::string data;         // raw DATA bytes (gRPC-framed messages)
    std::string header_block; // accumulating HEADERS/CONTINUATION fragments
    bool headers_done = false;
    // END_STREAM seen on a HEADERS frame whose block is still awaiting
    // CONTINUATION — completion is only signalled once the block inflates,
    // so a mux caller never wakes to half-parsed trailers
    bool end_after_headers = false;
    // completion flags: written under state_mu_ (the mux reader sets them,
    // waiting callers read them under the same mutex via mux_cv_)
    bool end_stream = false;
    bool reset = false;
    uint32_t reset_code = 0;
    // per-stream send budget (RFC 7540 §6.9); replenished by the peer's
    // WINDOW_UPDATEs for this stream — guarded by state_mu_
    long long send_window = 65535;
  };

  Error SendFrame(uint8_t type, uint8_t flags, uint32_t stream_id,
                  const std::string& payload);
  Error ReadFrameHdr(FrameHdr* hdr, const sockio::Deadline& dl);
  Error ProcessOneFrame(CallState* call, const sockio::Deadline& dl);
  // Which call does a frame for `id` belong to: `cur` (the caller-driven
  // unary/bidi call) or a registered mux call (then `*pin` keeps it alive
  // past concurrent unregistration).
  CallState* TargetFor(uint32_t id, CallState* cur,
                       std::shared_ptr<CallState>* pin);
  void MuxReaderLoop();
  void StopMux();
  Error SendHeaders(const std::string& path, const Headers& metadata,
                    uint32_t stream_id, uint64_t timeout_us, bool end_stream);
  Error SendGrpcMessage(const std::string& message, CallState* call,
                        bool end_stream, const sockio::Deadline& dl);
  Error InflateHeaderBlock(const std::string& block, Headers* out);
  static Error GrpcStatusToError(const Headers& h);
  Error ReplenishRecvWindow(uint32_t stream_id, size_t consumed);

  int fd_ = -1;
  TlsSession* tls_sess_ = nullptr;  // non-null: all IO rides TLS (grpcs)
  std::mutex write_mu_;  // interleaved frame writes (stream reader ACKs)
  void* inflater_ = nullptr;
  uint32_t next_stream_id_ = 1;
  // flow control (RFC 7540 §6.9): our send budget, replenished by the peer
  // (per-stream budgets live on each CallState)
  long long conn_send_window_ = 65535;
  uint32_t peer_initial_window_ = 65535;
  uint32_t peer_max_frame_ = 16384;
  size_t max_response_bytes_ = 0;
  // our receive budget: advertised big, replenished as data is consumed
  long long conn_recv_consumed_ = 0;
  std::mutex state_mu_;
  std::condition_variable window_cv_;
  // active bidi stream
  CallState stream_call_;
  bool stream_active_ = false;
  size_t stream_read_pos_ = 0;
  // multiplexed unary mode (guarded by state_mu_ unless noted)
  std::map<uint32_t, std::shared_ptr<CallState>> mux_calls_;
  std::mutex open_mu_;  // stream ids must hit the wire in open order
  std::thread mux_thread_;
  bool mux_on_ = false;
  bool mux_dead_ = false;
  Error mux_err_;
  std::condition_variable mux_cv_;
};

}  // namespace client
}  // namespace tc_tpu
