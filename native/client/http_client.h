// C++ HTTP/REST client.
//
// Parity target: reference src/c++/library/http_client.h (651 LoC) — same
// public API: factory Create, health/metadata/config/repository/statistics/
// trace/log/shm management methods, Infer + AsyncInfer, binary-over-HTTP
// framing with Inference-Header-Content-Length (http_client.cc:2098-2246).
//
// Transport re-design: the image has no libcurl headers, so the transport is
// a dependency-free HTTP/1.1 keep-alive connection pool over POSIX sockets.
// AsyncInfer runs on a fixed worker pool draining a request queue (the
// functional equivalent of the reference's curl-multi AsyncTransfer loop,
// http_client.cc:2249-2348, without hand-scheduling one thread over N easy
// handles — threads are cheap on a TPU VM host and the API is identical).
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "json.h"
#include "transport.h"

namespace tc_tpu {
namespace client {

using Parameters = std::map<std::string, std::string>;

// SSL options (API parity with reference http_client.h:45-86).  TLS is
// backed by the system libssl.so.3 resolved at runtime (tls.{h,cc}; the
// image ships no OpenSSL headers, so the needed ABI subset is declared
// locally).  `cert`/`key`/`ca_info` are file paths, as in the reference's
// libcurl-based options.  When libssl is absent, Create() with
// `use_ssl=true` fails loudly rather than silently speaking plaintext.
struct HttpSslOptions {
  enum class CERTTYPE { CERT_PEM, CERT_DER };
  enum class KEYTYPE { KEY_PEM, KEY_DER };
  bool verify_peer = true;
  bool verify_host = true;
  std::string ca_info;
  CERTTYPE cert_type = CERTTYPE::CERT_PEM;
  std::string cert;
  KEYTYPE key_type = KEYTYPE::KEY_PEM;
  std::string key;
};

class InferResultHttp;

class InferenceServerHttpClient : public InferenceServerClient {
 public:
  using OnCompleteFn = std::function<void(InferResult*)>;
  using OnMultiCompleteFn = std::function<void(std::vector<InferResult*>)>;

  // Body compression (reference http_client.h CompressionType; zlib-backed).
  enum class CompressionType { NONE, DEFLATE, GZIP };

  static Error Create(
      std::unique_ptr<InferenceServerHttpClient>* client,
      const std::string& server_url, bool verbose = false,
      size_t concurrency = 4, bool use_ssl = false,
      const HttpSslOptions& ssl_options = HttpSslOptions());
  ~InferenceServerHttpClient() override;

  Error IsServerLive(bool* live, const Headers& headers = Headers());
  Error IsServerReady(bool* ready, const Headers& headers = Headers());
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());

  Error ServerMetadata(std::string* server_metadata,
                       const Headers& headers = Headers());
  Error ModelMetadata(
      std::string* model_metadata, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());
  Error ModelConfig(
      std::string* model_config, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());

  Error ModelRepositoryIndex(std::string* repository_index,
                             const Headers& headers = Headers());
  Error LoadModel(
      const std::string& model_name, const Headers& headers = Headers(),
      const std::string& config = "",
      const std::map<std::string, std::vector<char>>& files = {});
  Error UnloadModel(
      const std::string& model_name, const Headers& headers = Headers());

  Error ModelInferenceStatistics(
      std::string* infer_stat, const std::string& model_name = "",
      const std::string& model_version = "",
      const Headers& headers = Headers());

  Error UpdateTraceSettings(
      std::string* response, const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {},
      const Headers& headers = Headers());
  Error GetTraceSettings(
      std::string* settings, const std::string& model_name = "",
      const Headers& headers = Headers());
  Error UpdateLogSettings(
      std::string* response,
      const std::map<std::string, std::string>& settings = {},
      const Headers& headers = Headers());
  Error GetLogSettings(
      std::string* settings, const Headers& headers = Headers());

  Error SystemSharedMemoryStatus(
      std::string* status, const std::string& region_name = "",
      const Headers& headers = Headers());
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0, const Headers& headers = Headers());
  Error UnregisterSystemSharedMemory(
      const std::string& name = "", const Headers& headers = Headers());
  // "Cuda" wire name kept for v2 compatibility; the handle is an XLA
  // device-buffer descriptor (xla_shared_memory.get_raw_handle).
  Error CudaSharedMemoryStatus(
      std::string* status, const std::string& region_name = "",
      const Headers& headers = Headers());
  Error RegisterCudaSharedMemory(
      const std::string& name, const std::vector<uint8_t>& raw_handle,
      size_t device_id, size_t byte_size, const Headers& headers = Headers());
  Error UnregisterCudaSharedMemory(
      const std::string& name = "", const Headers& headers = Headers());

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = Headers(),
      CompressionType request_compression_algorithm = CompressionType::NONE,
      CompressionType response_compression_algorithm = CompressionType::NONE);

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = Headers(),
      CompressionType request_compression_algorithm = CompressionType::NONE,
      CompressionType response_compression_algorithm = CompressionType::NONE);

  // Fan-out over multiple requests in one call (reference
  // http_client.cc:1911-2021).  `options`/`outputs` may hold one element
  // (broadcast to every request) or exactly `inputs.size()`; the single
  // `headers` map applies to every request.
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = Headers());
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = Headers());

 private:
  InferenceServerHttpClient(
      const std::string& url, bool verbose, size_t concurrency);

  using Response = HttpTransport::Response;

  Error Get(const std::string& path, const Headers& headers, Response* out);
  Error Post(
      const std::string& path, const std::string& body,
      const Headers& headers, Response* out, RequestTimers* timers = nullptr,
      uint64_t timeout_us = 0);
  static Error CheckResponse(const Response& resp);
  // One infer exchange: build headers, compress, post, decompress, parse.
  Error DoInfer(
      InferResult** result, const std::string& path, std::string body,
      size_t header_length, const Headers& headers, uint64_t timeout_us,
      CompressionType request_compression,
      CompressionType response_compression, RequestTimers* timers);

  Error BuildInferRequestBody(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs,
      std::string* body, size_t* header_length);

  std::unique_ptr<HttpTransport> transport_;
  size_t concurrency_;

  // async worker pool
  struct AsyncJob {
    OnCompleteFn callback;
    std::string path;
    std::string body;
    Headers headers;
    size_t header_length = 0;
    uint64_t timeout_us = 0;
    CompressionType request_compression = CompressionType::NONE;
    CompressionType response_compression = CompressionType::NONE;
  };
  void AsyncTransfer();
  std::mutex job_mu_;
  std::condition_variable job_cv_;
  std::deque<AsyncJob> jobs_;
  std::vector<std::thread> workers_;
  bool exiting_ = false;
};

}  // namespace client
}  // namespace tc_tpu
