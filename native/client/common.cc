#include "common.h"

#include <ostream>

namespace tc_tpu {
namespace client {

const Error Error::Success;

std::ostream& operator<<(std::ostream& out, const Error& err) {
  if (!err.IsOk()) out << "error: " << err.Message();
  return out;
}

//==============================================================================
Error InferInput::Create(
    InferInput** infer_input, const std::string& name,
    const std::vector<int64_t>& dims, const std::string& datatype) {
  if (name.empty()) return Error("input name must not be empty");
  *infer_input = new InferInput(name, dims, datatype);
  return Error::Success;
}

InferInput::InferInput(
    const std::string& name, const std::vector<int64_t>& dims,
    const std::string& datatype)
    : name_(name), shape_(dims), datatype_(datatype) {}

Error InferInput::SetShape(const std::vector<int64_t>& dims) {
  shape_ = dims;
  return Error::Success;
}

Error InferInput::AppendRaw(const uint8_t* input, size_t input_byte_size) {
  if (io_type_ == IOType::kSharedMemory) {
    return Error(
        "The input '" + name_ +
        "' has already been set with SetSharedMemory(); Reset() first");
  }
  io_type_ = IOType::kRaw;
  bufs_.emplace_back(input, input_byte_size);
  total_byte_size_ += input_byte_size;
  return Error::Success;
}

Error InferInput::AppendRaw(const std::vector<uint8_t>& input) {
  return AppendRaw(input.data(), input.size());
}

Error InferInput::AppendFromString(const std::vector<std::string>& input) {
  std::string serialized;
  SerializeStringTensor(input, &serialized);
  owned_.push_back(std::move(serialized));
  const std::string& stored = owned_.back();
  return AppendRaw(
      reinterpret_cast<const uint8_t*>(stored.data()), stored.size());
}

Error InferInput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  if (io_type_ == IOType::kRaw) {
    return Error(
        "The input '" + name_ +
        "' has already been set with AppendRaw(); Reset() first");
  }
  io_type_ = IOType::kSharedMemory;
  shm_region_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error InferInput::Reset() {
  io_type_ = IOType::kNone;
  bufs_.clear();
  owned_.clear();
  total_byte_size_ = 0;
  gather_index_ = 0;
  gather_offset_ = 0;
  shm_region_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

void InferInput::PrepareForRequest() const {
  gather_index_ = 0;
  gather_offset_ = 0;
}

Error InferInput::GetNext(
    uint8_t* buf, size_t size, size_t* input_bytes, bool* end_of_input) const {
  size_t copied = 0;
  while (copied < size && gather_index_ < bufs_.size()) {
    const auto& [ptr, len] = bufs_[gather_index_];
    size_t remaining = len - gather_offset_;
    size_t to_copy = std::min(remaining, size - copied);
    std::memcpy(buf + copied, ptr + gather_offset_, to_copy);
    copied += to_copy;
    gather_offset_ += to_copy;
    if (gather_offset_ == len) {
      ++gather_index_;
      gather_offset_ = 0;
    }
  }
  *input_bytes = copied;
  *end_of_input = (gather_index_ >= bufs_.size());
  return Error::Success;
}

Error InferInput::GetNext(
    const uint8_t** buf, size_t* input_bytes, bool* end_of_input) const {
  if (gather_index_ < bufs_.size()) {
    *buf = bufs_[gather_index_].first;
    *input_bytes = bufs_[gather_index_].second;
    ++gather_index_;
  } else {
    *buf = nullptr;
    *input_bytes = 0;
  }
  *end_of_input = (gather_index_ >= bufs_.size());
  return Error::Success;
}

//==============================================================================
Error InferRequestedOutput::Create(
    InferRequestedOutput** infer_output, const std::string& name,
    size_t class_count) {
  if (name.empty()) return Error("output name must not be empty");
  *infer_output = new InferRequestedOutput(name, class_count);
  return Error::Success;
}

Error InferRequestedOutput::SetSharedMemory(
    const std::string& region_name, size_t byte_size, size_t offset) {
  is_shm_ = true;
  shm_region_ = region_name;
  shm_byte_size_ = byte_size;
  shm_offset_ = offset;
  return Error::Success;
}

Error InferRequestedOutput::UnsetSharedMemory() {
  is_shm_ = false;
  shm_region_.clear();
  shm_byte_size_ = 0;
  shm_offset_ = 0;
  return Error::Success;
}

//==============================================================================
Error InferResult::StringData(
    const std::string& output_name, std::vector<std::string>* string_result) const {
  const uint8_t* buf = nullptr;
  size_t byte_size = 0;
  TC_RETURN_IF_ERROR(RawData(output_name, &buf, &byte_size));
  return DeserializeStringTensor(buf, byte_size, string_result);
}

Error InferResult::IsFinalResponse(bool* is_final_response) const {
  *is_final_response = true;
  return Error::Success;
}

Error InferResult::IsNullResponse(bool* is_null_response) const {
  *is_null_response = false;
  return Error::Success;
}

//==============================================================================
void InferenceServerClient::UpdateInferStat(const RequestTimers& timer) {
  std::lock_guard<std::mutex> lk(stat_mu_);
  infer_stat_.completed_request_count++;
  infer_stat_.cumulative_total_request_time_ns += timer.Duration(
      RequestTimers::Kind::REQUEST_START, RequestTimers::Kind::REQUEST_END);
  infer_stat_.cumulative_send_time_ns += timer.Duration(
      RequestTimers::Kind::SEND_START, RequestTimers::Kind::SEND_END);
  infer_stat_.cumulative_receive_time_ns += timer.Duration(
      RequestTimers::Kind::RECV_START, RequestTimers::Kind::RECV_END);
}

//==============================================================================
void SerializeStringTensor(
    const std::vector<std::string>& strings, std::string* out) {
  for (const auto& s : strings) {
    uint32_t len = static_cast<uint32_t>(s.size());
    out->append(reinterpret_cast<const char*>(&len), sizeof(len));  // LE host
    out->append(s);
  }
}

Error DeserializeStringTensor(
    const uint8_t* data, size_t size, std::vector<std::string>* out) {
  size_t pos = 0;
  while (pos < size) {
    if (pos + sizeof(uint32_t) > size) {
      return Error("string tensor is truncated: bad length prefix");
    }
    uint32_t len;
    std::memcpy(&len, data + pos, sizeof(len));
    pos += sizeof(len);
    if (pos + len > size) {
      return Error("string tensor is truncated: element exceeds buffer");
    }
    out->emplace_back(reinterpret_cast<const char*>(data + pos), len);
    pos += len;
  }
  return Error::Success;
}

}  // namespace client
}  // namespace tc_tpu
