// Shared low-level socket I/O helpers (deadline-aware connect/read/write),
// used by the HTTP/1.1 transport (transport.cc) and the HTTP/2 gRPC layer
// (h2.cc).  Header-only; everything lives in tc_tpu::client::sockio.
#pragma once

#include <arpa/inet.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <chrono>
#include <cstdio>
#include <string>

#include "common.h"

namespace tc_tpu {
namespace client {
namespace sockio {

// Total-transfer deadline (reference CURLOPT_TIMEOUT_MS semantics: one
// clock covers connect + send + receive).  DNS resolution is the one step
// not covered (getaddrinfo has no timeout hook); clients talk to
// localhost/IPs in practice.
struct Deadline {
  bool enabled = false;
  std::chrono::steady_clock::time_point at{};

  static Deadline In(uint64_t us) {
    Deadline d;
    if (us > 0) {
      d.enabled = true;
      d.at = std::chrono::steady_clock::now() + std::chrono::microseconds(us);
    }
    return d;
  }

  long long RemainingUs() const {
    if (!enabled) return -1;
    return std::chrono::duration_cast<std::chrono::microseconds>(
               at - std::chrono::steady_clock::now())
        .count();
  }
};

inline void SetSocketTimeout(int fd, int option, long long timeout_us) {
  struct timeval tv;
  tv.tv_sec = static_cast<time_t>(timeout_us / 1000000);
  tv.tv_usec = static_cast<suseconds_t>(timeout_us % 1000000);
  if (timeout_us > 0 && tv.tv_sec == 0 && tv.tv_usec == 0) tv.tv_usec = 1;
  ::setsockopt(fd, SOL_SOCKET, option, &tv, sizeof(tv));
}

// recv against the deadline: >0 bytes, 0 EOF, -1 socket error, -2 expired.
inline ssize_t RecvDl(int fd, char* buf, size_t n, const Deadline& dl) {
  if (dl.enabled) {
    long long rem = dl.RemainingUs();
    if (rem <= 0) return -2;
    SetSocketTimeout(fd, SO_RCVTIMEO, rem);
  }
  ssize_t r = ::recv(fd, buf, n, 0);
  if (r < 0 && dl.enabled && (errno == EAGAIN || errno == EWOULDBLOCK)) {
    return -2;
  }
  return r;
}

// 0 ok, -1 error/EOF, -2 deadline expired.
inline int ReadExactDl(int fd, char* buf, size_t n, const Deadline& dl) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = RecvDl(fd, buf + got, n - got, dl);
    if (r == -2) return -2;
    if (r <= 0) return -1;
    got += static_cast<size_t>(r);
  }
  return 0;
}

inline int WriteAllDl(int fd, const char* buf, size_t n, const Deadline& dl) {
  size_t sent = 0;
  while (sent < n) {
    if (dl.enabled) {
      long long rem = dl.RemainingUs();
      if (rem <= 0) return -2;
      SetSocketTimeout(fd, SO_SNDTIMEO, rem);
    }
    ssize_t w = ::send(fd, buf + sent, n - sent, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && dl.enabled && (errno == EAGAIN || errno == EWOULDBLOCK)) {
        return -2;
      }
      return -1;
    }
    sent += static_cast<size_t>(w);
  }
  return 0;
}

inline bool WriteAll(int fd, const char* buf, size_t n) {
  return WriteAllDl(fd, buf, n, Deadline()) == 0;
}

// Resolve + connect (poll-based so the deadline covers it) + TCP_NODELAY;
// returns -1 with *err set on failure.
inline int ConnectTcp(
    const std::string& host, int port, Error* err,
    const Deadline& dl = Deadline()) {
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  char port_str[16];
  snprintf(port_str, sizeof(port_str), "%d", port);
  int rc = ::getaddrinfo(host.c_str(), port_str, &hints, &res);
  if (rc != 0) {
    *err = Error(std::string("failed to resolve host: ") + gai_strerror(rc));
    return -1;
  }
  int fd = -1;
  bool timed_out = false;
  for (auto* ai = res; ai != nullptr; ai = ai->ai_next) {
    fd = ::socket(ai->ai_family,
                  ai->ai_socktype | SOCK_NONBLOCK, ai->ai_protocol);
    if (fd < 0) continue;
    int crc = ::connect(fd, ai->ai_addr, ai->ai_addrlen);
    if (crc != 0 && errno == EINPROGRESS) {
      long long rem = dl.enabled ? dl.RemainingUs() : -1;
      if (dl.enabled && rem <= 0) {
        timed_out = true;
        ::close(fd);
        fd = -1;
        break;
      }
      struct pollfd pfd = {fd, POLLOUT, 0};
      int prc = ::poll(&pfd, 1, dl.enabled ? static_cast<int>(rem / 1000 + 1)
                                           : -1);
      int so_err = 0;
      socklen_t len = sizeof(so_err);
      if (prc > 0 &&
          ::getsockopt(fd, SOL_SOCKET, SO_ERROR, &so_err, &len) == 0 &&
          so_err == 0) {
        crc = 0;
      } else {
        if (prc == 0) timed_out = true;
        crc = -1;
      }
    }
    if (crc == 0) {
      // restore blocking mode for the request I/O
      int flags = ::fcntl(fd, F_GETFL, 0);
      ::fcntl(fd, F_SETFL, flags & ~O_NONBLOCK);
      break;
    }
    ::close(fd);
    fd = -1;
    if (timed_out) break;
  }
  ::freeaddrinfo(res);
  if (fd < 0) {
    *err = Error(
        timed_out ? "Deadline Exceeded: timed out connecting to " + host +
                        ":" + port_str
                  : "failed to connect to " + host + ":" + port_str);
    return -1;
  }
  int one = 1;
  ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return fd;
}

// TCP keepalive probes (gRPC keepalive-ping translation; see
// HttpTransport::SetTcpKeepAlive).
inline void EnableTcpKeepAlive(int fd, int idle_s, int intvl_s) {
  if (idle_s <= 0) return;
  int one = 1;
  ::setsockopt(fd, SOL_SOCKET, SO_KEEPALIVE, &one, sizeof(one));
  ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPIDLE, &idle_s, sizeof(idle_s));
  if (intvl_s > 0) {
    ::setsockopt(fd, IPPROTO_TCP, TCP_KEEPINTVL, &intvl_s, sizeof(intvl_s));
  }
}

}  // namespace sockio
}  // namespace client
}  // namespace tc_tpu
