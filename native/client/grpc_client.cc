#include "grpc_client.h"

#include <cstring>

#include "infer_multi.h"

namespace tc_tpu {
namespace client {

namespace {

constexpr char kServicePath[] = "inference.GRPCInferenceService";

std::string Frame(const std::string& payload, uint8_t flags = 0) {
  std::string out;
  out.reserve(5 + payload.size());
  out.push_back(static_cast<char>(flags));
  uint32_t len = static_cast<uint32_t>(payload.size());
  out.push_back(static_cast<char>((len >> 24) & 0xFF));
  out.push_back(static_cast<char>((len >> 16) & 0xFF));
  out.push_back(static_cast<char>((len >> 8) & 0xFF));
  out.push_back(static_cast<char>(len & 0xFF));
  out.append(payload);
  return out;
}

// Split a grpc-web body into data frames + trailer text.
Error ParseFrames(
    const std::string& body, std::vector<std::string>* data_frames,
    std::string* trailers) {
  size_t pos = 0;
  while (pos + 5 <= body.size()) {
    uint8_t flags = static_cast<uint8_t>(body[pos]);
    uint32_t len = (static_cast<uint8_t>(body[pos + 1]) << 24) |
                   (static_cast<uint8_t>(body[pos + 2]) << 16) |
                   (static_cast<uint8_t>(body[pos + 3]) << 8) |
                   static_cast<uint8_t>(body[pos + 4]);
    pos += 5;
    if (pos + len > body.size()) {
      return Error("truncated grpc-web frame in response");
    }
    if (flags & 0x80) {
      trailers->assign(body, pos, len);
    } else {
      data_frames->emplace_back(body.substr(pos, len));
    }
    pos += len;
  }
  return Error::Success;
}

std::string PercentDecode(const std::string& s) {
  std::string out;
  for (size_t i = 0; i < s.size(); ++i) {
    if (s[i] == '%' && i + 2 < s.size()) {
      out.push_back(static_cast<char>(
          strtol(s.substr(i + 1, 2).c_str(), nullptr, 16)));
      i += 2;
    } else {
      out.push_back(s[i]);
    }
  }
  return out;
}

Error StatusFromTrailers(const std::string& trailers) {
  if (trailers.empty()) {
    // A well-formed grpc-web response always ends in a trailers frame with
    // grpc-status; a missing frame means the body was truncated.
    return Error("response missing grpc-web trailers frame");
  }
  int status = 0;
  std::string message;
  size_t pos = 0;
  while (pos < trailers.size()) {
    size_t nl = trailers.find("\r\n", pos);
    if (nl == std::string::npos) nl = trailers.size();
    std::string line = trailers.substr(pos, nl - pos);
    pos = nl + 2;
    auto colon = line.find(':');
    if (colon == std::string::npos) continue;
    std::string key = line.substr(0, colon);
    std::string value = line.substr(colon + 1);
    if (key == "grpc-status") status = atoi(value.c_str());
    if (key == "grpc-message") message = PercentDecode(value);
  }
  if (status == 0) return Error::Success;
  return Error(message.empty() ? ("rpc failed with status " +
                                  std::to_string(status))
                               : message);
}

void SetParam(pb::ModelInferRequest* request, const std::string& key,
              int64_t value) {
  (*request->mutable_parameters())[key].set_int64_param(value);
}

// Result over a ModelInferResponse (reference InferResultGrpc,
// grpc_client.cc).  raw_output_contents are indexed positionally across
// non-shm outputs (reference _infer_result.py:63-97).
class InferResultGrpcImpl : public InferResult {
 public:
  explicit InferResultGrpcImpl(pb::ModelInferResponse response)
      : response_(std::move(response)) {
    // raw_output_contents holds entries ONLY for non-shm outputs, in output
    // order (reference positional indexing, _infer_result.py:63-97)
    int raw_index = 0;
    for (const auto& out : response_.outputs()) {
      if (out.parameters().count("shared_memory_region")) continue;
      if (raw_index < response_.raw_output_contents_size()) {
        raw_index_[out.name()] = raw_index;
        ++raw_index;
      }
    }
  }

  Error ModelName(std::string* name) const override {
    *name = response_.model_name();
    return Error::Success;
  }
  Error ModelVersion(std::string* version) const override {
    *version = response_.model_version();
    return Error::Success;
  }
  Error Id(std::string* id) const override {
    *id = response_.id();
    return Error::Success;
  }

  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override {
    const auto* out = FindOutput(output_name);
    if (!out) return Error("output '" + output_name + "' not found");
    shape->assign(out->shape().begin(), out->shape().end());
    return Error::Success;
  }

  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override {
    const auto* out = FindOutput(output_name);
    if (!out) return Error("output '" + output_name + "' not found");
    *datatype = out->datatype();
    return Error::Success;
  }

  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override {
    auto it = raw_index_.find(output_name);
    if (it == raw_index_.end()) {
      return Error("output '" + output_name + "' has no raw data");
    }
    const std::string& blob = response_.raw_output_contents(it->second);
    *buf = reinterpret_cast<const uint8_t*>(blob.data());
    *byte_size = blob.size();
    return Error::Success;
  }

  Error IsFinalResponse(bool* is_final) const override {
    auto it = response_.parameters().find("triton_final_response");
    *is_final = it != response_.parameters().end() && it->second.bool_param();
    return Error::Success;
  }

  Error IsNullResponse(bool* is_null) const override {
    *is_null = response_.outputs_size() == 0;
    return Error::Success;
  }

  Error RequestStatus() const override { return Error::Success; }
  std::string DebugString() const override { return response_.DebugString(); }

  const pb::ModelInferResponse& Response() const { return response_; }

 private:
  const pb::ModelInferResponse::InferOutputTensor* FindOutput(
      const std::string& name) const {
    for (const auto& out : response_.outputs()) {
      if (out.name() == name) return &out;
    }
    return nullptr;
  }

  pb::ModelInferResponse response_;
  std::map<std::string, int> raw_index_;
};

// Process-global transport cache (reference channel cache,
// grpc_client.cc:47-152): up to TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT
// (default 6) clients of the same url share one transport — its pooled
// sockets — before a fresh one is created.  (h2 connection pools stay
// per-client; the shared resource is the transport's socket pool, the
// closest analog of grpc channel sharing.)
struct TransportCache {
  struct Entry {
    std::shared_ptr<HttpTransport> transport;
    int share_count = 0;
  };
  std::mutex mu;
  std::map<std::string, std::vector<Entry>> by_url;

  static TransportCache& Get() {
    static TransportCache* cache = new TransportCache();
    return *cache;
  }

  static int MaxShare() {
    const char* env = getenv("TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT");
    int n = env != nullptr ? atoi(env) : 6;
    return n > 0 ? n : 6;
  }

  std::shared_ptr<HttpTransport> Acquire(
      const std::string& url, const std::string& host, int port) {
    int max_share = MaxShare();
    std::lock_guard<std::mutex> lk(mu);
    auto& entries = by_url[url];
    for (auto& e : entries) {
      if (e.share_count < max_share) {
        ++e.share_count;
        return e.transport;
      }
    }
    entries.push_back({std::make_shared<HttpTransport>(host, port, 8), 1});
    return entries.back().transport;
  }

  void Release(const std::string& url,
               const std::shared_ptr<HttpTransport>& transport) {
    std::lock_guard<std::mutex> lk(mu);
    auto it = by_url.find(url);
    if (it == by_url.end()) return;
    auto& entries = it->second;
    for (size_t i = 0; i < entries.size(); ++i) {
      if (entries[i].transport == transport) {
        if (--entries[i].share_count <= 0) {
          entries.erase(entries.begin() + i);
        }
        break;
      }
    }
    if (entries.empty()) by_url.erase(it);
  }
};

}  // namespace

//==============================================================================
Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, bool verbose, bool use_cached_channel) {
  client->reset(new InferenceServerGrpcClient(server_url, verbose));
  if ((*client)->transport_->port() <= 0) {
    return Error("invalid server url '" + server_url + "'");
  }
  if (use_cached_channel) {
    auto shared = TransportCache::Get().Acquire(
        server_url, (*client)->transport_->host(),
        (*client)->transport_->port());
    (*client)->transport_ = shared;
    (*client)->cached_url_ = server_url;
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, bool verbose, bool use_ssl,
    const GrpcSslOptions& ssl_options) {
  TC_RETURN_IF_ERROR(Create(client, server_url, verbose,
                            /*use_cached_channel=*/!use_ssl));
  if (use_ssl) {
    HttpSslOptionsView view;
    view.ca_info = ssl_options.root_certificates;
    view.cert = ssl_options.certificate_chain;
    view.key = ssl_options.private_key;
    TC_RETURN_IF_ERROR((*client)->transport_->EnableTls(view));
    // mode probe stays automatic: the first RPC offers TLS+ALPN "h2" —
    // a stock secure gRPC port negotiates h2 (real grpcs); the HTTPS web
    // bridge negotiates http/1.1 and the client falls back to gRPC-Web
    // over TLS
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, bool verbose,
    const KeepAliveOptions& keepalive_options) {
  // keepalive mutates transport state: never share a cached transport
  TC_RETURN_IF_ERROR(Create(client, server_url, verbose,
                            /*use_cached_channel=*/false));
  // INT_MAX means "disabled", matching gRPC's default
  if (keepalive_options.keepalive_time_ms > 0 &&
      keepalive_options.keepalive_time_ms != 0x7fffffff) {
    int idle_s = keepalive_options.keepalive_time_ms / 1000;
    int intvl_s = keepalive_options.keepalive_timeout_ms / 1000;
    (*client)->transport_->SetTcpKeepAlive(
        idle_s > 0 ? idle_s : 1, intvl_s > 0 ? intvl_s : 1);
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Create(
    std::unique_ptr<InferenceServerGrpcClient>* client,
    const std::string& server_url, const ChannelArguments& channel_args,
    bool verbose) {
  KeepAliveOptions ka;
  for (const auto& kv : channel_args.args()) {
    if (kv.first == "grpc.keepalive_time_ms") {
      ka.keepalive_time_ms = atoi(kv.second.c_str());
    } else if (kv.first == "grpc.keepalive_timeout_ms") {
      ka.keepalive_timeout_ms = atoi(kv.second.c_str());
    }
  }
  // delegates so the ms→s keepalive translation lives in ONE place
  TC_RETURN_IF_ERROR(Create(client, server_url, verbose, ka));
  for (const auto& kv : channel_args.args()) {
    if (kv.first == "grpc.max_receive_message_length") {
      long cap = atol(kv.second.c_str());
      if (cap > 0)
        (*client)->transport_->SetMaxResponseBytes(static_cast<size_t>(cap));
    } else if (kv.first == "grpc.max_send_message_length") {
      long cap = atol(kv.second.c_str());
      if (cap > 0)
        (*client)->transport_->SetMaxRequestBytes(static_cast<size_t>(cap));
    } else if (
        kv.first != "grpc.keepalive_time_ms" &&
        kv.first != "grpc.keepalive_timeout_ms" && verbose) {
      fprintf(stderr, "channel arg ignored by socket transport: %s=%s\n",
              kv.first.c_str(), kv.second.c_str());
    }
  }
  return Error::Success;
}

InferenceServerGrpcClient::InferenceServerGrpcClient(
    const std::string& url, bool verbose)
    : InferenceServerClient(verbose) {
  std::string host = url;
  int port = 8001;
  auto colon = url.rfind(':');
  if (colon != std::string::npos) {
    host = url.substr(0, colon);
    port = atoi(url.substr(colon + 1).c_str());
  }
  transport_.reset(new HttpTransport(host, port, 8));
}

InferenceServerGrpcClient::~InferenceServerGrpcClient() {
  FinishStream();  // closes an open stream; harmless error when none
  if (!cached_url_.empty()) {
    TransportCache::Get().Release(cached_url_, transport_);
  }
  if (stream_reader_.joinable()) stream_reader_.join();
  {
    std::lock_guard<std::mutex> lk(job_mu_);
    exiting_ = true;
  }
  job_cv_.notify_all();
  for (auto& w : workers_) {
    if (w.joinable()) w.join();
  }
}

//==============================================================================
// Transport-mode selection: real gRPC over h2c when the endpoint speaks
// HTTP/2 (the stock gRPC port — reference grpc++ wire,
// /root/reference/src/c++/library/grpc_client.cc:1093-1150), gRPC-Web over
// the HTTP/1.1 bridge otherwise.  TC_TPU_GRPC_TRANSPORT=h2|web pins it.
Error InferenceServerGrpcClient::EnsureMode(uint64_t timeout_us) {
  std::lock_guard<std::mutex> lk(mode_mu_);
  if (mode_ != Mode::kUndecided) return Error::Success;
  const char* force = getenv("TC_TPU_GRPC_TRANSPORT");
  if (force != nullptr && std::string(force) == "web") {
    mode_ = Mode::kWeb;
    return Error::Success;
  }
  if (!H2Available()) {
    if (force != nullptr && std::string(force) == "h2") {
      return Error(
          "TC_TPU_GRPC_TRANSPORT=h2 but libnghttp2 (HPACK) is unavailable");
    }
    mode_ = Mode::kWeb;
    return Error::Success;
  }
  auto conn = std::make_unique<H2GrpcConnection>();
  bool not_http2 = false;
  Error err = conn->Connect(
      transport_->host(), transport_->port(), &not_http2,
      transport_->keepalive_idle_s(), transport_->keepalive_intvl_s(),
      timeout_us, transport_->tls_context());
  if (err.IsOk()) {
    mode_ = Mode::kH2;
    h2_idle_.emplace_back(std::move(conn));
    if (verbose_) {
      fprintf(stderr, "grpc transport: %s\n",
              transport_->tls_enabled() ? "grpcs (h2 over TLS)" : "h2c");
    }
    return Error::Success;
  }
  if (force != nullptr && std::string(force) == "h2") return err;
  if (not_http2) {
    mode_ = Mode::kWeb;
    if (verbose_) fprintf(stderr, "grpc transport: grpc-web bridge\n");
    return Error::Success;
  }
  // connection-level failure (server down?): don't pin a mode — surface
  // the error and re-probe on the next call
  return err;
}

Error InferenceServerGrpcClient::AcquireH2(
    std::unique_ptr<H2GrpcConnection>* conn, uint64_t timeout_us) {
  {
    std::lock_guard<std::mutex> lk(mode_mu_);
    if (!h2_idle_.empty()) {
      *conn = std::move(h2_idle_.back());
      h2_idle_.pop_back();
      return Error::Success;
    }
  }
  *conn = std::make_unique<H2GrpcConnection>();
  bool not_http2 = false;
  return (*conn)->Connect(
      transport_->host(), transport_->port(), &not_http2,
      transport_->keepalive_idle_s(), transport_->keepalive_intvl_s(),
      timeout_us, transport_->tls_context());
}

void InferenceServerGrpcClient::ReleaseH2(
    std::unique_ptr<H2GrpcConnection> conn, bool reusable) {
  if (!reusable || !conn->connected()) return;
  std::lock_guard<std::mutex> lk(mode_mu_);
  if (h2_idle_.size() < 8) h2_idle_.emplace_back(std::move(conn));
}

Error InferenceServerGrpcClient::Call(
    const std::string& method, const google::protobuf::Message& request,
    google::protobuf::Message* response, const Headers& headers,
    RequestTimers* timers, uint64_t timeout_us) {
  TC_RETURN_IF_ERROR(EnsureMode(timeout_us));
  bool h2;
  {
    std::lock_guard<std::mutex> lk(mode_mu_);
    h2 = (mode_ == Mode::kH2);
  }
  if (h2) return CallH2(method, request, response, headers, timers, timeout_us);
  return CallWeb(method, request, response, headers, timers, timeout_us);
}

Error InferenceServerGrpcClient::AcquireMux(
    std::shared_ptr<H2GrpcConnection>* conn, uint64_t timeout_us) {
  std::shared_ptr<H2GrpcConnection> fresh;
  {
    std::lock_guard<std::mutex> lk(mode_mu_);
    if (h2_mux_ != nullptr && h2_mux_->MuxHealthy()) {
      *conn = h2_mux_;
      return Error::Success;
    }
    if (!h2_idle_.empty()) {
      // promote the EnsureMode probe (or a pooled idle conn): the client
      // then runs ONE socket total
      fresh = std::shared_ptr<H2GrpcConnection>(h2_idle_.back().release());
      h2_idle_.pop_back();
    }
  }
  if (fresh == nullptr) {
    // connect OUTSIDE mode_mu_: a reconnect to an unreachable server must
    // stall only mux callers, not every pooled call behind the lock
    fresh = std::make_shared<H2GrpcConnection>();
    bool not_http2 = false;
    TC_RETURN_IF_ERROR(fresh->Connect(
        transport_->host(), transport_->port(), &not_http2,
        transport_->keepalive_idle_s(), transport_->keepalive_intvl_s(),
        timeout_us, transport_->tls_context()));
  }
  // set once before the channel is shared — per-call sets would race
  fresh->SetMaxResponseBytes(transport_->max_response_bytes());
  TC_RETURN_IF_ERROR(fresh->StartMux());
  std::lock_guard<std::mutex> lk(mode_mu_);
  if (h2_mux_ != nullptr && h2_mux_->MuxHealthy()) {
    // another caller won the rebuild race; theirs is the channel
    *conn = h2_mux_;
    return Error::Success;
  }
  h2_mux_ = fresh;
  *conn = fresh;
  return Error::Success;
}

Error InferenceServerGrpcClient::CallH2(
    const std::string& method, const google::protobuf::Message& request,
    google::protobuf::Message* response, const Headers& headers,
    RequestTimers* timers, uint64_t timeout_us) {
  std::string body = request.SerializeAsString();
  if (transport_->max_request_bytes() > 0 &&
      body.size() > transport_->max_request_bytes()) {
    return Error(
        "request exceeds maximum send message size of " +
        std::to_string(transport_->max_request_bytes()) + " bytes");
  }
  const std::string path = std::string("/") + kServicePath + "/" + method;
  // Default: grpc++-style multiplexing — every concurrent unary call on
  // this client shares ONE socket (reference grpc_client.cc:47-152).
  // TC_TPU_GRPC_UNARY_MUX=0 pins the one-call-per-pooled-connection
  // fallback; a mux channel that dies mid-call also falls back for that
  // call while the next AcquireMux builds a replacement.
  const char* mux_env = getenv("TC_TPU_GRPC_UNARY_MUX");
  if (mux_env == nullptr || std::string(mux_env) != "0") {
    std::shared_ptr<H2GrpcConnection> mux;
    Error merr = AcquireMux(&mux, timeout_us);
    if (merr.IsOk()) {
      std::string resp;
      Error err = mux->MuxUnaryCall(path, body, headers, &resp, timeout_us,
                                    timers);
      if (err.IsOk()) {
        if (!response->ParseFromString(resp)) {
          return Error("failed to parse " + method + " response");
        }
        if (verbose_) fprintf(stderr, "%s -> ok\n", method.c_str());
        return Error::Success;
      }
      if (!mux->MuxHealthy()) {
        // channel died under this call: drop it so the next call builds a
        // fresh one.  Do NOT transparently re-send this call — the server
        // may already have executed it (gRPC only retries requests that
        // never reached the server; a silent replay would double-step
        // sequence models)
        std::lock_guard<std::mutex> lk(mode_mu_);
        if (h2_mux_ == mux) h2_mux_.reset();
      }
      return err;
    }
    // mux channel could not be built (nothing was sent): the pooled path
    // below serves this call
  }
  std::unique_ptr<H2GrpcConnection> conn;
  TC_RETURN_IF_ERROR(AcquireH2(&conn, timeout_us));
  conn->SetMaxResponseBytes(transport_->max_response_bytes());
  std::string resp;
  Error err = conn->UnaryCall(
      std::string("/") + kServicePath + "/" + method, body, headers, &resp,
      timeout_us, timers);
  // a clean grpc-status error leaves the connection reusable; transport
  // and protocol failures Close() it inside UnaryCall, and ReleaseH2 drops
  // disconnected handles
  ReleaseH2(std::move(conn), true);
  TC_RETURN_IF_ERROR(err);
  if (!response->ParseFromString(resp)) {
    return Error("failed to parse " + method + " response");
  }
  if (verbose_) {
    fprintf(stderr, "%s -> ok\n", method.c_str());
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::CallWeb(
    const std::string& method, const google::protobuf::Message& request,
    google::protobuf::Message* response, const Headers& headers,
    RequestTimers* timers, uint64_t timeout_us) {
  std::string body = Frame(request.SerializeAsString());
  Headers h = headers;
  h["Content-Type"] = "application/grpc-web+proto";
  HttpTransport::Response resp;
  TC_RETURN_IF_ERROR(transport_->Request(
      "POST", std::string(kServicePath) + "/" + method, body, h, &resp,
      timers, timeout_us));
  if (resp.status != 200) {
    return Error("grpc-web request failed with HTTP status " +
                 std::to_string(resp.status));
  }
  std::vector<std::string> frames;
  std::string trailers;
  TC_RETURN_IF_ERROR(ParseFrames(resp.body, &frames, &trailers));
  TC_RETURN_IF_ERROR(StatusFromTrailers(trailers));
  if (frames.empty()) return Error("rpc returned no response message");
  if (!response->ParseFromString(frames[0])) {
    return Error("failed to parse " + method + " response");
  }
  if (verbose_) {
    fprintf(stderr, "%s -> ok\n", method.c_str());
  }
  return Error::Success;
}

//==============================================================================
Error InferenceServerGrpcClient::IsServerLive(bool* live, const Headers& headers) {
  pb::ServerLiveResponse resp;
  TC_RETURN_IF_ERROR(Call("ServerLive", pb::ServerLiveRequest(), &resp, headers));
  *live = resp.live();
  return Error::Success;
}

Error InferenceServerGrpcClient::IsServerReady(bool* ready, const Headers& headers) {
  pb::ServerReadyResponse resp;
  TC_RETURN_IF_ERROR(
      Call("ServerReady", pb::ServerReadyRequest(), &resp, headers));
  *ready = resp.ready();
  return Error::Success;
}

Error InferenceServerGrpcClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  pb::ModelReadyRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  pb::ModelReadyResponse resp;
  TC_RETURN_IF_ERROR(Call("ModelReady", req, &resp, headers));
  *ready = resp.ready();
  return Error::Success;
}

Error InferenceServerGrpcClient::ServerMetadata(
    pb::ServerMetadataResponse* server_metadata, const Headers& headers) {
  return Call("ServerMetadata", pb::ServerMetadataRequest(), server_metadata,
              headers);
}

Error InferenceServerGrpcClient::ModelMetadata(
    pb::ModelMetadataResponse* model_metadata, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  pb::ModelMetadataRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Call("ModelMetadata", req, model_metadata, headers);
}

Error InferenceServerGrpcClient::ModelConfig(
    pb::ModelConfigResponse* model_config, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  pb::ModelConfigRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Call("ModelConfig", req, model_config, headers);
}

Error InferenceServerGrpcClient::ModelRepositoryIndex(
    pb::RepositoryIndexResponse* repository_index, const Headers& headers) {
  return Call("RepositoryIndex", pb::RepositoryIndexRequest(),
              repository_index, headers);
}

Error InferenceServerGrpcClient::LoadModel(
    const std::string& model_name, const Headers& headers,
    const std::string& config,
    const std::map<std::string, std::vector<char>>& files) {
  pb::RepositoryModelLoadRequest req;
  req.set_model_name(model_name);
  if (!config.empty()) {
    (*req.mutable_parameters())["config"].set_string_param(config);
  }
  for (const auto& kv : files) {
    (*req.mutable_parameters())[kv.first].set_bytes_param(
        std::string(kv.second.begin(), kv.second.end()));
  }
  pb::RepositoryModelLoadResponse resp;
  return Call("RepositoryModelLoad", req, &resp, headers);
}

Error InferenceServerGrpcClient::UnloadModel(
    const std::string& model_name, const Headers& headers) {
  pb::RepositoryModelUnloadRequest req;
  req.set_model_name(model_name);
  pb::RepositoryModelUnloadResponse resp;
  return Call("RepositoryModelUnload", req, &resp, headers);
}

Error InferenceServerGrpcClient::ModelInferenceStatistics(
    pb::ModelStatisticsResponse* infer_stat, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  pb::ModelStatisticsRequest req;
  req.set_name(model_name);
  req.set_version(model_version);
  return Call("ModelStatistics", req, infer_stat, headers);
}

Error InferenceServerGrpcClient::UpdateTraceSettings(
    pb::TraceSettingResponse* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings,
    const Headers& headers) {
  pb::TraceSettingRequest req;
  req.set_model_name(model_name);
  for (const auto& kv : settings) {
    auto& value = (*req.mutable_settings())[kv.first];
    for (const auto& v : kv.second) value.add_value(v);
  }
  return Call("TraceSetting", req, response, headers);
}

Error InferenceServerGrpcClient::GetTraceSettings(
    pb::TraceSettingResponse* settings, const std::string& model_name,
    const Headers& headers) {
  pb::TraceSettingRequest req;
  req.set_model_name(model_name);
  return Call("TraceSetting", req, settings, headers);
}

Error InferenceServerGrpcClient::UpdateLogSettings(
    pb::LogSettingsResponse* response,
    const std::map<std::string, std::string>& settings,
    const Headers& headers) {
  pb::LogSettingsRequest req;
  for (const auto& kv : settings) {
    auto& value = (*req.mutable_settings())[kv.first];
    if (kv.second == "true" || kv.second == "false") {
      value.set_bool_param(kv.second == "true");
    } else if (!kv.second.empty() &&
               kv.second.find_first_not_of("0123456789") == std::string::npos) {
      value.set_uint32_param(
          static_cast<uint32_t>(strtoul(kv.second.c_str(), nullptr, 10)));
    } else {
      value.set_string_param(kv.second);
    }
  }
  return Call("LogSettings", req, response, headers);
}

Error InferenceServerGrpcClient::GetLogSettings(
    pb::LogSettingsResponse* settings, const Headers& headers) {
  pb::LogSettingsRequest req;
  return Call("LogSettings", req, settings, headers);
}

Error InferenceServerGrpcClient::SystemSharedMemoryStatus(
    pb::SystemSharedMemoryStatusResponse* status,
    const std::string& region_name, const Headers& headers) {
  pb::SystemSharedMemoryStatusRequest req;
  req.set_name(region_name);
  return Call("SystemSharedMemoryStatus", req, status, headers);
}

Error InferenceServerGrpcClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& headers) {
  pb::SystemSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_key(key);
  req.set_offset(offset);
  req.set_byte_size(byte_size);
  pb::SystemSharedMemoryRegisterResponse resp;
  return Call("SystemSharedMemoryRegister", req, &resp, headers);
}

Error InferenceServerGrpcClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& headers) {
  pb::SystemSharedMemoryUnregisterRequest req;
  req.set_name(name);
  pb::SystemSharedMemoryUnregisterResponse resp;
  return Call("SystemSharedMemoryUnregister", req, &resp, headers);
}

Error InferenceServerGrpcClient::CudaSharedMemoryStatus(
    pb::CudaSharedMemoryStatusResponse* status,
    const std::string& region_name, const Headers& headers) {
  pb::CudaSharedMemoryStatusRequest req;
  req.set_name(region_name);
  return Call("CudaSharedMemoryStatus", req, status, headers);
}

Error InferenceServerGrpcClient::RegisterCudaSharedMemory(
    const std::string& name, const std::vector<uint8_t>& raw_handle,
    size_t device_id, size_t byte_size, const Headers& headers) {
  pb::CudaSharedMemoryRegisterRequest req;
  req.set_name(name);
  req.set_raw_handle(raw_handle.data(), raw_handle.size());
  req.set_device_id(device_id);
  req.set_byte_size(byte_size);
  pb::CudaSharedMemoryRegisterResponse resp;
  return Call("CudaSharedMemoryRegister", req, &resp, headers);
}

Error InferenceServerGrpcClient::UnregisterCudaSharedMemory(
    const std::string& name, const Headers& headers) {
  pb::CudaSharedMemoryUnregisterRequest req;
  req.set_name(name);
  pb::CudaSharedMemoryUnregisterResponse resp;
  return Call("CudaSharedMemoryUnregister", req, &resp, headers);
}

//==============================================================================
Error InferenceServerGrpcClient::BuildInferRequest(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    pb::ModelInferRequest* request) {
  request->set_model_name(options.model_name_);
  request->set_model_version(options.model_version_);
  request->set_id(options.request_id_);
  if (!options.sequence_id_str_.empty()) {
    (*request->mutable_parameters())["sequence_id"].set_string_param(
        options.sequence_id_str_);
  } else if (options.sequence_id_ != 0) {
    SetParam(request, "sequence_id",
             static_cast<int64_t>(options.sequence_id_));
  }
  if (options.sequence_id_ != 0 || !options.sequence_id_str_.empty()) {
    (*request->mutable_parameters())["sequence_start"].set_bool_param(
        options.sequence_start_);
    (*request->mutable_parameters())["sequence_end"].set_bool_param(
        options.sequence_end_);
  }
  if (options.priority_ != 0) {
    SetParam(request, "priority", static_cast<int64_t>(options.priority_));
  }
  if (options.server_timeout_us_ != 0) {
    SetParam(request, "timeout",
             static_cast<int64_t>(options.server_timeout_us_));
  }
  if (options.triton_enable_empty_final_response_) {
    (*request->mutable_parameters())["triton_enable_empty_final_response"]
        .set_bool_param(true);
  }
  for (const auto& kv : options.request_parameters_) {
    (*request->mutable_parameters())[kv.first].set_string_param(kv.second);
  }

  for (InferInput* input : inputs) {
    auto* tensor = request->add_inputs();
    tensor->set_name(input->Name());
    tensor->set_datatype(input->Datatype());
    for (int64_t d : input->Shape()) tensor->add_shape(d);
    if (input->Type() == InferInput::IOType::kSharedMemory) {
      auto* params = tensor->mutable_parameters();
      (*params)["shared_memory_region"].set_string_param(
          input->SharedMemoryRegion());
      (*params)["shared_memory_byte_size"].set_int64_param(
          static_cast<int64_t>(input->SharedMemoryByteSize()));
      if (input->SharedMemoryOffset() != 0) {
        (*params)["shared_memory_offset"].set_int64_param(
            static_cast<int64_t>(input->SharedMemoryOffset()));
      }
    } else {
      input->PrepareForRequest();
      std::string* blob = request->add_raw_input_contents();
      blob->reserve(input->TotalByteSize());
      bool end = false;
      while (!end) {
        const uint8_t* ptr = nullptr;
        size_t len = 0;
        TC_RETURN_IF_ERROR(input->GetNext(&ptr, &len, &end));
        if (ptr && len) blob->append(reinterpret_cast<const char*>(ptr), len);
      }
    }
  }

  for (const InferRequestedOutput* output : outputs) {
    auto* tensor = request->add_outputs();
    tensor->set_name(output->Name());
    if (output->ClassCount() > 0) {
      (*tensor->mutable_parameters())["classification"].set_int64_param(
          static_cast<int64_t>(output->ClassCount()));
    }
    if (output->IsSharedMemory()) {
      auto* params = tensor->mutable_parameters();
      (*params)["shared_memory_region"].set_string_param(
          output->SharedMemoryRegion());
      (*params)["shared_memory_byte_size"].set_int64_param(
          static_cast<int64_t>(output->SharedMemoryByteSize()));
      if (output->SharedMemoryOffset() != 0) {
        (*params)["shared_memory_offset"].set_int64_param(
            static_cast<int64_t>(output->SharedMemoryOffset()));
      }
    }
  }
  return Error::Success;
}

Error InferenceServerGrpcClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers) {
  RequestTimers timers;
  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
  pb::ModelInferRequest request;
  TC_RETURN_IF_ERROR(BuildInferRequest(options, inputs, outputs, &request));
  pb::ModelInferResponse response;
  TC_RETURN_IF_ERROR(Call(
      "ModelInfer", request, &response, headers, &timers,
      options.client_timeout_us_));
  *result = new InferResultGrpcImpl(std::move(response));
  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  UpdateInferStat(timers);
  return Error::Success;
}

Error InferenceServerGrpcClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  AsyncJob job;
  job.callback = std::move(callback);
  job.headers = headers;
  job.timeout_us = options.client_timeout_us_;
  TC_RETURN_IF_ERROR(
      BuildInferRequest(options, inputs, outputs, &job.request));
  {
    std::lock_guard<std::mutex> lk(job_mu_);
    if (workers_.empty()) {
      for (int i = 0; i < 4; ++i) {
        workers_.emplace_back(&InferenceServerGrpcClient::AsyncTransfer, this);
      }
    }
    jobs_.push_back(std::move(job));
  }
  job_cv_.notify_one();
  return Error::Success;
}

void InferenceServerGrpcClient::AsyncTransfer() {
  while (true) {
    AsyncJob job;
    {
      std::unique_lock<std::mutex> lk(job_mu_);
      job_cv_.wait(lk, [this] { return exiting_ || !jobs_.empty(); });
      if (exiting_ && jobs_.empty()) return;
      job = std::move(jobs_.front());
      jobs_.pop_front();
    }
    RequestTimers timers;
    timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
    pb::ModelInferResponse response;
    Error err = Call(
        "ModelInfer", job.request, &response, job.headers, &timers,
        job.timeout_us);
    InferResult* result = nullptr;
    if (err.IsOk()) {
      result = new InferResultGrpcImpl(std::move(response));
      timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
      std::lock_guard<std::mutex> lk(job_mu_);
      UpdateInferStat(timers);
    } else {
      result = new ErrorResult(err);
    }
    job.callback(result);
  }
}

//==============================================================================
Error InferenceServerGrpcClient::InferMulti(
    std::vector<InferResult*>* results,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  return multi_detail::InferMultiImpl(
      results, options, inputs, outputs,
      [&](InferResult** result, const InferOptions& opt, const auto& ins,
          const auto& outs) {
        return Infer(result, opt, ins, outs, headers);
      });
}

Error InferenceServerGrpcClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  return multi_detail::AsyncInferMultiImpl(
      std::move(callback), options, inputs, outputs,
      [&](OnCompleteFn cb, const InferOptions& opt, const auto& ins,
          const auto& outs) {
        return AsyncInfer(std::move(cb), opt, ins, outs, headers);
      });
}

//==============================================================================
Error InferenceServerGrpcClient::StartStream(
    OnCompleteFn callback, const Headers& headers) {
  {
    std::lock_guard<std::mutex> lk(stream_write_mu_);
    if (stream_active_) {
      return Error(
          "cannot start another stream with one already running; call "
          "FinishStream() first (it returns the previous stream's status)");
    }
  }
  // reap a reader left from a previous stream torn down via the destructor
  // path; after a normal FinishStream the thread is already joined
  if (stream_reader_.joinable()) stream_reader_.join();
  if (callback == nullptr) {
    return Error("callback must not be null for StartStream");
  }
  TC_RETURN_IF_ERROR(EnsureMode(0));
  bool h2;
  {
    std::lock_guard<std::mutex> lk(mode_mu_);
    h2 = (mode_ == Mode::kH2);
  }
  if (h2) {
    // real gRPC bidi stream on a dedicated h2c connection (reference
    // ClientReaderWriter, grpc_client.cc:1377-1416)
    auto hconn = std::make_unique<H2GrpcConnection>();
    bool not_http2 = false;
    TC_RETURN_IF_ERROR(hconn->Connect(
        transport_->host(), transport_->port(), &not_http2,
        transport_->keepalive_idle_s(), transport_->keepalive_intvl_s(),
        0, transport_->tls_context()));
    TC_RETURN_IF_ERROR(hconn->StartStream(
        std::string("/") + kServicePath + "/ModelStreamInfer", headers));
    stream_callback_ = std::move(callback);
    {
      std::lock_guard<std::mutex> lk(stream_err_mu_);
      stream_final_error_ = Error::Success;
    }
    {
      std::lock_guard<std::mutex> lk(stream_write_mu_);
      h2_stream_conn_ = std::move(hconn);
      stream_active_ = true;
    }
    stream_reader_ =
        std::thread(&InferenceServerGrpcClient::StreamReadLoopH2, this);
    return Error::Success;
  }
  auto conn = std::make_unique<DuplexConnection>();
  TC_RETURN_IF_ERROR(conn->Open(
      transport_->host(), transport_->port(),
      std::string(kServicePath) + "/ModelStreamInfer", headers,
      transport_->keepalive_idle_s(), transport_->keepalive_intvl_s(),
      transport_->tls_context()));
  int status = 0;
  Headers resp_headers;
  TC_RETURN_IF_ERROR(conn->ReadResponseHeaders(&status, &resp_headers));
  if (status != 200) {
    return Error("stream request failed with HTTP status " +
                 std::to_string(status));
  }
  stream_callback_ = std::move(callback);
  {
    std::lock_guard<std::mutex> lk(stream_err_mu_);
    stream_final_error_ = Error::Success;
  }
  {
    std::lock_guard<std::mutex> lk(stream_write_mu_);
    stream_conn_ = std::move(conn);
    stream_active_ = true;
  }
  stream_reader_ =
      std::thread(&InferenceServerGrpcClient::StreamReadLoop, this);
  return Error::Success;
}

// Reader thread (reference AsyncStreamTransfer, grpc_client.cc:1628-1673):
// parses grpc-web frames incrementally off the open response body and fires
// the user callback for every message the moment it arrives.
void InferenceServerGrpcClient::StreamReadLoop() {
  std::string buf;
  bool done = false;
  std::string trailers;
  while (!done) {
    std::string bytes;
    Error err = stream_conn_->ReadSome(&bytes, &done);
    if (!err.IsOk()) {
      {
        std::lock_guard<std::mutex> lk(stream_err_mu_);
        stream_final_error_ = err;
      }
      // surface the broken stream to the user, not just to FinishStream
      stream_callback_(new ErrorResult(err));
      return;
    }
    buf += bytes;
    // drain complete grpc-web frames
    while (buf.size() >= 5) {
      uint8_t flags = static_cast<uint8_t>(buf[0]);
      uint32_t len = (static_cast<uint8_t>(buf[1]) << 24) |
                     (static_cast<uint8_t>(buf[2]) << 16) |
                     (static_cast<uint8_t>(buf[3]) << 8) |
                     static_cast<uint8_t>(buf[4]);
      if (buf.size() < 5u + len) break;
      std::string payload = buf.substr(5, len);
      buf.erase(0, 5u + len);
      if (flags & 0x80) {
        trailers = payload;
        continue;
      }
      pb::ModelStreamInferResponse stream_resp;
      if (!stream_resp.ParseFromString(payload)) {
        stream_callback_(
            new ErrorResult(Error("failed to parse stream response")));
      } else if (!stream_resp.error_message().empty()) {
        stream_callback_(new ErrorResult(Error(stream_resp.error_message())));
      } else {
        stream_callback_(new InferResultGrpcImpl(stream_resp.infer_response()));
      }
    }
  }
  std::lock_guard<std::mutex> lk(stream_err_mu_);
  stream_final_error_ = StatusFromTrailers(trailers);
}

// Reader thread for the h2c stream: gRPC messages straight off the HTTP/2
// DATA frames (reference AsyncStreamTransfer, grpc_client.cc:1628-1673).
void InferenceServerGrpcClient::StreamReadLoopH2() {
  for (;;) {
    std::string msg;
    bool done = false;
    Error err = h2_stream_conn_->StreamRead(&msg, &done);
    if (done) {
      {
        std::lock_guard<std::mutex> lk(stream_err_mu_);
        stream_final_error_ = err;
      }
      if (!err.IsOk()) {
        // surface the broken stream to the user, not just to FinishStream
        // (same contract as the web-path reader loop)
        stream_callback_(new ErrorResult(err));
      }
      return;
    }
    pb::ModelStreamInferResponse stream_resp;
    if (!stream_resp.ParseFromString(msg)) {
      stream_callback_(
          new ErrorResult(Error("failed to parse stream response")));
    } else if (!stream_resp.error_message().empty()) {
      stream_callback_(new ErrorResult(Error(stream_resp.error_message())));
    } else {
      stream_callback_(new InferResultGrpcImpl(stream_resp.infer_response()));
    }
  }
}

Error InferenceServerGrpcClient::AsyncStreamInfer(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs) {
  pb::ModelInferRequest request;
  TC_RETURN_IF_ERROR(BuildInferRequest(options, inputs, outputs, &request));
  std::lock_guard<std::mutex> lk(stream_write_mu_);
  if (!stream_active_) {
    return Error("stream not available, StartStream() must be called first");
  }
  if (h2_stream_conn_ != nullptr) {
    return h2_stream_conn_->StreamWrite(request.SerializeAsString());
  }
  return stream_conn_->WriteChunk(Frame(request.SerializeAsString()));
}

Error InferenceServerGrpcClient::FinishStream() {
  if (stream_reader_.joinable() &&
      std::this_thread::get_id() == stream_reader_.get_id()) {
    // joining ourselves would throw resource_deadlock_would_occur
    return Error(
        "FinishStream must not be called from the stream callback");
  }
  Error write_err;
  bool h2 = false;
  {
    std::lock_guard<std::mutex> lk(stream_write_mu_);
    if (!stream_active_) {
      return Error("no active stream");
    }
    stream_active_ = false;
    h2 = (h2_stream_conn_ != nullptr);
    write_err = h2 ? h2_stream_conn_->StreamWritesDone()
                   : stream_conn_->WriteEnd();
  }
  if (stream_reader_.joinable()) stream_reader_.join();
  {
    std::lock_guard<std::mutex> lk(stream_write_mu_);
    if (h2) {
      h2_stream_conn_->Close();
      h2_stream_conn_.reset();
    } else {
      stream_conn_->Close();
      stream_conn_.reset();
    }
  }
  Error final_err;
  {
    std::lock_guard<std::mutex> lk(stream_err_mu_);
    final_err = stream_final_error_;
  }
  if (!final_err.IsOk()) return final_err;
  return write_err;
}

}  // namespace client
}  // namespace tc_tpu
