// Native load generator: the C++ core of the perf_analyzer contract
// (reference src/c++/perf_analyzer/README.md:28-30 — infer/sec and latency
// percentiles over concurrency sweeps; upstream's tool is native C++, so
// this framework's native client gets one too, alongside the full-featured
// Python tpu-perf-analyzer).
//
// Modes:
//   closed loop  --concurrency-range start:end[:step]
//       N threads, each its own client over the shared channel cache,
//       back-to-back Infer() for the measurement window.
//   open loop    --request-rate-range start:end[:step]
//       requests fire on a precomputed constant or Poisson schedule and
//       LATENCY IS MEASURED FROM THE SCHEDULED SEND TIME, so queue buildup
//       counts against the server (coordinated-omission-free, same
//       contract as the Python tool); slots the thread pool never reached
//       are reported as unsent.
//
// Inputs are synthesized from the model's metadata (shape -1 -> batch in
// dim 0 else 1), like perf_analyzer: numeric dtypes get deterministic
// small-int fills, BYTES gets fixed-width strings.

#include <algorithm>
#include <atomic>
#include <chrono>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <memory>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include "grpc_client.h"
#include "http_client.h"
#include "json.h"
#include "xla_shm_utils.h"

namespace tc = tc_tpu::client;
using Clock = std::chrono::steady_clock;

namespace {

struct TensorSpec {
  std::string name;
  std::string datatype;
  std::vector<int64_t> dims;
};

struct Options {
  std::string url = "localhost:8001";
  std::string protocol = "grpc";  // grpc | http
  std::string model;
  int batch = 1;
  int window_ms = 3000;
  int warmup_ms = 500;
  bool json_out = false;
  // closed loop
  int conc_start = 1, conc_end = 4, conc_step = 1;
  bool have_conc = false;
  // open loop
  double rate_start = 0, rate_end = 0, rate_step = 0;
  bool have_rate = false;
  std::string distribution = "constant";  // constant | poisson
  int max_threads = 32;
  // data path: none (wire tensors) | system (POSIX shm) | xla (device
  // staging regions — the cudashm analog)
  std::string shared_memory = "none";
  size_t output_shm_size = 1 << 20;  // reference --output-shared-memory-size
};

bool
ParseRange(const char* s, double* a, double* b, double* c)
{
  double x = 0, y = 0, z = 0;
  int n = sscanf(s, "%lf:%lf:%lf", &x, &y, &z);
  if (n < 2) return false;
  *a = x;
  *b = y;
  *c = (n == 3) ? z : 1;
  return *c > 0 && y >= x;
}

size_t
DtypeSize(const std::string& dt)
{
  if (dt == "BOOL" || dt == "INT8" || dt == "UINT8") return 1;
  if (dt == "INT16" || dt == "UINT16" || dt == "FP16" || dt == "BF16")
    return 2;
  if (dt == "INT32" || dt == "UINT32" || dt == "FP32") return 4;
  if (dt == "INT64" || dt == "UINT64" || dt == "FP64") return 8;
  return 0;  // BYTES handled separately
}

// Deterministic small-value fill: valid for id/index inputs (vocab ids,
// pixel bytes) and harmless for float features.
void
FillTensor(const std::string& dt, size_t n_elems, std::vector<uint8_t>* buf)
{
  size_t esz = DtypeSize(dt);
  buf->resize(n_elems * esz);
  for (size_t i = 0; i < n_elems; ++i) {
    // BOOL payloads must stay canonical 0/1: bytes 2..9 are not valid
    // booleans and a validating decoder may reject them
    long v = static_cast<long>(dt == "BOOL" ? i % 2 : i % 10);
    uint8_t* p = buf->data() + i * esz;
    if (dt == "FP32") {
      float f = static_cast<float>(v);
      memcpy(p, &f, 4);
    } else if (dt == "FP64") {
      double d = static_cast<double>(v);
      memcpy(p, &d, 8);
    } else if (dt == "FP16" || dt == "BF16") {
      // zeros are valid halfs; keep it simple
      memset(p, 0, 2);
    } else {
      // integer family, little-endian
      long long vv = v;
      memcpy(p, &vv, esz);
    }
  }
}

class Workload {
 public:
  Workload(const Options& opt, std::vector<TensorSpec> specs,
           std::vector<std::string> output_names)
      : opt_(opt), specs_(std::move(specs)),
        output_names_(std::move(output_names))
  {
    for (const auto& s : specs_) {
      std::vector<int64_t> shape = s.dims;
      for (size_t i = 0; i < shape.size(); ++i) {
        if (shape[i] < 0) shape[i] = (i == 0) ? opt_.batch : 1;
      }
      size_t n = 1;
      for (auto d : shape) n *= static_cast<size_t>(d);
      shapes_.push_back(shape);
      std::vector<uint8_t> buf;
      if (s.datatype != "BYTES") FillTensor(s.datatype, n, &buf);
      const size_t nbytes = buf.size();
      fills_.push_back(std::move(buf));
      counts_.push_back(n);
      // 64-byte-aligned packing for the single shared input region
      offsets_.push_back(in_region_bytes_);
      in_region_bytes_ += (nbytes + 63) & ~size_t(63);
    }
  }

  // One client + one reusable input set per worker thread.
  struct Ctx {
    std::unique_ptr<tc::InferenceServerGrpcClient> grpc;
    std::unique_ptr<tc::InferenceServerHttpClient> http;
    std::vector<tc::InferInput*> inputs;
    std::vector<const tc::InferRequestedOutput*> outputs;
    // system-shm regions (inputs packed in one, outputs strided in one)
    struct SysRegion {
      std::string name, key;
      int fd = -1;
      void* base = nullptr;
      size_t size = 0;
    };
    SysRegion sys_in, sys_out;
    // xla staging regions (cudashm analog)
    tc::XlaShmHandle xla_in, xla_out;
    bool have_xla_in = false, have_xla_out = false;

    void UnregisterSys(const std::string& name)
    {
      if (grpc != nullptr) {
        grpc->UnregisterSystemSharedMemory(name);
      } else if (http != nullptr) {
        http->UnregisterSystemSharedMemory(name);
      }
    }
    void UnregisterXla(const std::string& name)
    {
      if (grpc != nullptr) {
        grpc->UnregisterCudaSharedMemory(name);
      } else if (http != nullptr) {
        http->UnregisterCudaSharedMemory(name);
      }
    }
    ~Ctx()
    {
      for (auto* in : inputs) delete in;
      for (const auto* out : outputs) delete out;
      for (auto* r : {&sys_in, &sys_out}) {
        if (r->base != nullptr) {
          UnregisterSys(r->name);
          munmap(r->base, r->size);
        }
        if (r->fd >= 0) close(r->fd);
        // unlink whenever shm_open ran — a failed mmap must not leak the
        // region file in /dev/shm
        if (!r->key.empty()) shm_unlink(r->key.c_str());
      }
      if (have_xla_in) {
        UnregisterXla(xla_in.triton_shm_name);
        tc::DestroyXlaSharedMemoryRegion(&xla_in);
      }
      if (have_xla_out) {
        UnregisterXla(xla_out.triton_shm_name);
        tc::DestroyXlaSharedMemoryRegion(&xla_out);
      }
    }
  };

  bool MakeCtx(Ctx* ctx, std::string* err)
  {
    tc::Error e;
    if (opt_.protocol == "grpc") {
      e = tc::InferenceServerGrpcClient::Create(&ctx->grpc, opt_.url);
    } else {
      e = tc::InferenceServerHttpClient::Create(&ctx->http, opt_.url);
    }
    if (!e.IsOk()) {
      *err = e.Message();
      return false;
    }
    if (opt_.shared_memory != "none") return SetupShm(ctx, err);
    for (size_t i = 0; i < specs_.size(); ++i) {
      tc::InferInput* in = nullptr;
      e = tc::InferInput::Create(&in, specs_[i].name, shapes_[i],
                                 specs_[i].datatype);
      if (!e.IsOk()) {
        *err = e.Message();
        return false;
      }
      if (specs_[i].datatype == "BYTES") {
        // numeric strings: valid for string-identity AND
        // string-arithmetic models (reference simple_string contract)
        std::vector<std::string> strs(counts_[i], "1");
        in->AppendFromString(strs);
      } else {
        in->AppendRaw(fills_[i].data(), fills_[i].size());
      }
      ctx->inputs.push_back(in);
    }
    return true;
  }

  // Shared-memory data path: inputs packed into one region written once
  // before the clock starts; outputs strided through a second region of
  // --output-shared-memory-size bytes each (reference perf_analyzer
  // --shared-memory=system|cuda contract; xla is the cudashm analog).
  bool SetupShm(Ctx* ctx, std::string* err)
  {
    static std::atomic<uint64_t> uniq{0};
    const uint64_t id = uniq.fetch_add(1);
    char tag[64];
    snprintf(tag, sizeof(tag), "%d_%llu", static_cast<int>(getpid()),
             static_cast<unsigned long long>(id));
    const size_t out_bytes = output_names_.size() * opt_.output_shm_size;
    // a model with no declared outputs has nothing to bind a region to:
    // inputs still ride shm, outputs stay on the wire (out_bytes == 0
    // would otherwise surface as an obscure mmap EINVAL)
    const bool want_out = !output_names_.empty();
    tc::Error e;
    std::string in_name, out_name;
    if (opt_.shared_memory == "system") {
      std::vector<Ctx::SysRegion*> regions{&ctx->sys_in};
      if (want_out) regions.push_back(&ctx->sys_out);
      for (auto* spec : regions) {
        bool is_in = (spec == &ctx->sys_in);
        spec->name = std::string(is_in ? "perf_in_" : "perf_out_") + tag;
        spec->key = "/" + spec->name;
        spec->size = is_in ? in_region_bytes_ : out_bytes;
        shm_unlink(spec->key.c_str());
        spec->fd = shm_open(spec->key.c_str(), O_RDWR | O_CREAT, 0600);
        if (spec->fd < 0 ||
            ftruncate(spec->fd, static_cast<off_t>(spec->size)) != 0) {
          *err = "shm_open/ftruncate failed for " + spec->key;
          return false;
        }
        spec->base = mmap(nullptr, spec->size, PROT_READ | PROT_WRITE,
                          MAP_SHARED, spec->fd, 0);
        if (spec->base == MAP_FAILED) {
          spec->base = nullptr;
          *err = "mmap failed for " + spec->key;
          return false;
        }
      }
      for (size_t i = 0; i < specs_.size(); ++i) {
        memcpy(static_cast<uint8_t*>(ctx->sys_in.base) + offsets_[i],
               fills_[i].data(), fills_[i].size());
      }
      auto reg = [&](const Ctx::SysRegion& r) {
        return (ctx->grpc != nullptr)
                   ? ctx->grpc->RegisterSystemSharedMemory(r.name, r.key,
                                                           r.size)
                   : ctx->http->RegisterSystemSharedMemory(r.name, r.key,
                                                           r.size);
      };
      e = reg(ctx->sys_in);
      if (e.IsOk() && want_out) e = reg(ctx->sys_out);
      if (!e.IsOk()) {
        *err = e.Message();
        return false;
      }
      in_name = ctx->sys_in.name;
      out_name = ctx->sys_out.name;
    } else {  // xla
      e = tc::CreateXlaSharedMemoryRegion(
          &ctx->xla_in, std::string("perf_xin_") + tag, in_region_bytes_, 0);
      if (e.IsOk()) ctx->have_xla_in = true;
      if (e.IsOk() && want_out) {
        e = tc::CreateXlaSharedMemoryRegion(
            &ctx->xla_out, std::string("perf_xout_") + tag, out_bytes, 0);
        if (e.IsOk()) ctx->have_xla_out = true;
      }
      for (size_t i = 0; e.IsOk() && i < specs_.size(); ++i) {
        e = tc::SetXlaSharedMemoryRegion(ctx->xla_in, fills_[i].data(),
                                         fills_[i].size(), offsets_[i]);
      }
      auto reg = [&](const tc::XlaShmHandle& h, size_t size) {
        std::vector<uint8_t> raw;
        tc::Error er = tc::GetXlaSharedMemoryRawHandle(h, &raw);
        if (!er.IsOk()) return er;
        return (ctx->grpc != nullptr)
                   ? ctx->grpc->RegisterCudaSharedMemory(h.triton_shm_name,
                                                         raw, 0, size)
                   : ctx->http->RegisterCudaSharedMemory(h.triton_shm_name,
                                                         raw, 0, size);
      };
      if (e.IsOk()) e = reg(ctx->xla_in, in_region_bytes_);
      if (e.IsOk() && want_out) e = reg(ctx->xla_out, out_bytes);
      if (!e.IsOk()) {
        *err = e.Message();
        return false;
      }
      in_name = ctx->xla_in.triton_shm_name;
      out_name = ctx->xla_out.triton_shm_name;
    }
    for (size_t i = 0; i < specs_.size(); ++i) {
      tc::InferInput* in = nullptr;
      e = tc::InferInput::Create(&in, specs_[i].name, shapes_[i],
                                 specs_[i].datatype);
      if (e.IsOk()) e = in->SetSharedMemory(in_name, fills_[i].size(),
                                            offsets_[i]);
      if (!e.IsOk()) {
        *err = e.Message();
        return false;
      }
      ctx->inputs.push_back(in);
    }
    for (size_t i = 0; i < output_names_.size(); ++i) {
      tc::InferRequestedOutput* out = nullptr;
      e = tc::InferRequestedOutput::Create(&out, output_names_[i]);
      if (e.IsOk()) e = out->SetSharedMemory(out_name, opt_.output_shm_size,
                                             i * opt_.output_shm_size);
      if (!e.IsOk()) {
        *err = e.Message();
        return false;
      }
      ctx->outputs.push_back(out);
    }
    return true;
  }

  bool InferOnce(Ctx* ctx, std::string* err)
  {
    tc::InferOptions options(opt_.model);
    tc::InferResult* result = nullptr;
    tc::Error e = (ctx->grpc != nullptr)
                      ? ctx->grpc->Infer(&result, options, ctx->inputs,
                                         ctx->outputs)
                      : ctx->http->Infer(&result, options, ctx->inputs,
                                         ctx->outputs);
    if (!e.IsOk()) {
      *err = e.Message();
      return false;
    }
    bool ok = result->RequestStatus().IsOk();
    if (!ok) *err = result->RequestStatus().Message();
    delete result;
    return ok;
  }

 private:
  const Options& opt_;
  std::vector<TensorSpec> specs_;
  std::vector<std::string> output_names_;
  std::vector<std::vector<int64_t>> shapes_;
  std::vector<std::vector<uint8_t>> fills_;
  std::vector<size_t> counts_;
  std::vector<size_t> offsets_;
  size_t in_region_bytes_ = 0;
};

// `v` must be sorted ascending (callers sort once per report).
double
Percentile(const std::vector<double>& v, double q)
{
  if (v.empty()) return 0;
  size_t idx = static_cast<size_t>(q * (v.size() - 1) + 0.5);
  return v[std::min(idx, v.size() - 1)];
}

void
Report(const Options& opt, const char* mode, double level, size_t completed,
       double window_s, std::vector<double>* lat_us, size_t unsent,
       double send_lag_p99_us)
{
  double thr = completed / window_s;
  std::sort(lat_us->begin(), lat_us->end());
  double p50 = Percentile(*lat_us, 0.50);
  double p90 = Percentile(*lat_us, 0.90);
  double p99 = Percentile(*lat_us, 0.99);
  if (opt.json_out) {
    printf(
        "{\"mode\": \"%s\", \"level\": %g, \"throughput_infer_per_sec\": "
        "%.1f, \"latency_p50_us\": %.0f, \"latency_p90_us\": %.0f, "
        "\"latency_p99_us\": %.0f, \"completed\": %zu, \"unsent\": %zu, "
        "\"send_lag_p99_us\": %.0f}\n",
        mode, level, thr, p50, p90, p99, completed, unsent, send_lag_p99_us);
  } else if (strcmp(mode, "concurrency") == 0) {
    printf(
        "Concurrency: %g, throughput: %.1f infer/sec, latency p50: %.0f "
        "usec, p90: %.0f usec, p99: %.0f usec\n",
        level, thr, p50, p90, p99);
  } else {
    printf(
        "Request rate: %g, throughput: %.1f infer/sec, latency p50: %.0f "
        "usec, p99: %.0f usec, send-lag p99: %.0f usec, unsent: %zu\n",
        level, thr, p50, p99, send_lag_p99_us, unsent);
  }
  fflush(stdout);
}

int
RunClosedLoop(const Options& opt, Workload* wl)
{
  for (int c = opt.conc_start; c <= opt.conc_end; c += opt.conc_step) {
    std::vector<std::unique_ptr<Workload::Ctx>> ctxs;
    for (int t = 0; t < c; ++t) {
      auto ctx = std::make_unique<Workload::Ctx>();
      std::string err;
      if (!wl->MakeCtx(ctx.get(), &err)) {
        fprintf(stderr, "FAILED: client setup: %s\n", err.c_str());
        return 1;
      }
      ctxs.push_back(std::move(ctx));
    }
    std::atomic<bool> stop{false};
    std::atomic<bool> failed{false};
    std::atomic<size_t> completed{0};
    std::vector<std::vector<double>> lat(c);
    auto warm_end =
        Clock::now() + std::chrono::milliseconds(opt.warmup_ms);
    auto start = warm_end;
    auto deadline = start + std::chrono::milliseconds(opt.window_ms);
    std::vector<std::thread> threads;
    for (int t = 0; t < c; ++t) {
      threads.emplace_back([&, t]() {
        std::string err;
        while (!stop.load(std::memory_order_relaxed)) {
          auto t0 = Clock::now();
          if (t0 >= deadline) break;
          if (!wl->InferOnce(ctxs[t].get(), &err)) {
            fprintf(stderr, "FAILED: infer: %s\n", err.c_str());
            failed.store(true);
            stop.store(true);
            break;
          }
          auto t1 = Clock::now();
          if (t0 >= start && t1 <= deadline) {
            lat[t].push_back(
                std::chrono::duration<double, std::micro>(t1 - t0).count());
            completed.fetch_add(1, std::memory_order_relaxed);
          }
        }
      });
    }
    for (auto& th : threads) th.join();
    if (failed.load()) return 1;
    std::vector<double> all;
    for (auto& v : lat) all.insert(all.end(), v.begin(), v.end());
    Report(opt, "concurrency", c, completed.load(),
           opt.window_ms / 1000.0, &all, 0, 0);
  }
  return 0;
}

int
RunOpenLoop(const Options& opt, Workload* wl)
{
  for (double r = opt.rate_start; r <= opt.rate_end + 1e-9;
       r += opt.rate_step) {
    // precomputed schedule over the window (seeded => reproducible)
    std::vector<double> sched_s;
    {
      std::mt19937_64 rng(12345);
      std::exponential_distribution<double> exp_gap(r);
      double t = 0, horizon = opt.window_ms / 1000.0;
      while (true) {
        t += (opt.distribution == "poisson") ? exp_gap(rng) : (1.0 / r);
        if (t >= horizon) break;
        sched_s.push_back(t);
      }
    }
    int n_threads = std::min<int>(opt.max_threads,
                                  std::max(1, static_cast<int>(r / 4) + 1));
    std::vector<std::unique_ptr<Workload::Ctx>> ctxs;
    for (int t = 0; t < n_threads; ++t) {
      auto ctx = std::make_unique<Workload::Ctx>();
      std::string err;
      if (!wl->MakeCtx(ctx.get(), &err)) {
        fprintf(stderr, "FAILED: client setup: %s\n", err.c_str());
        return 1;
      }
      ctxs.push_back(std::move(ctx));
    }
    // one warmup request per client
    for (auto& ctx : ctxs) {
      std::string err;
      if (!wl->InferOnce(ctx.get(), &err)) {
        fprintf(stderr, "FAILED: warmup infer: %s\n", err.c_str());
        return 1;
      }
    }
    std::atomic<size_t> next{0};
    std::atomic<bool> failed{false};
    std::vector<std::vector<double>> lat(n_threads), lag(n_threads);
    auto start = Clock::now();
    auto deadline = start + std::chrono::milliseconds(opt.window_ms);
    std::vector<std::thread> threads;
    for (int t = 0; t < n_threads; ++t) {
      threads.emplace_back([&, t]() {
        std::string err;
        while (!failed.load(std::memory_order_relaxed)) {
          size_t slot = next.fetch_add(1, std::memory_order_relaxed);
          if (slot >= sched_s.size()) break;
          auto sched = start + std::chrono::duration_cast<Clock::duration>(
                                   std::chrono::duration<double>(
                                       sched_s[slot]));
          std::this_thread::sleep_until(sched);
          auto t0 = Clock::now();
          if (t0 >= deadline) break;  // counts as unsent (no latency)
          if (!wl->InferOnce(ctxs[t].get(), &err)) {
            fprintf(stderr, "FAILED: infer: %s\n", err.c_str());
            failed.store(true);
            break;
          }
          auto t1 = Clock::now();
          // latency from the SCHEDULED send time: queueing counts
          lat[t].push_back(
              std::chrono::duration<double, std::micro>(t1 - sched).count());
          lag[t].push_back(
              std::chrono::duration<double, std::micro>(t0 - sched).count());
        }
      });
    }
    for (auto& th : threads) th.join();
    if (failed.load()) return 1;
    std::vector<double> all, lags;
    for (auto& v : lat) all.insert(all.end(), v.begin(), v.end());
    for (auto& v : lag) lags.insert(lags.end(), v.begin(), v.end());
    size_t sent = all.size();
    size_t unsent = sched_s.size() - std::min(sched_s.size(), sent);
    double wall = std::chrono::duration<double>(
                      Clock::now() - start).count();
    std::sort(lags.begin(), lags.end());
    Report(opt, "request_rate", r, sent, std::max(wall, 1e-9), &all, unsent,
           Percentile(lags, 0.99));
  }
  return 0;
}

bool
FetchSpecs(const Options& opt, std::vector<TensorSpec>* specs,
           std::vector<std::string>* output_names, std::string* err)
{
  if (opt.protocol == "grpc") {
    std::unique_ptr<tc::InferenceServerGrpcClient> client;
    tc::Error e = tc::InferenceServerGrpcClient::Create(&client, opt.url);
    if (!e.IsOk()) {
      *err = e.Message();
      return false;
    }
    inference::ModelMetadataResponse meta;
    e = client->ModelMetadata(&meta, opt.model);
    if (!e.IsOk()) {
      *err = e.Message();
      return false;
    }
    for (const auto& in : meta.inputs()) {
      TensorSpec s;
      s.name = in.name();
      s.datatype = in.datatype();
      for (auto d : in.shape()) s.dims.push_back(d);
      specs->push_back(std::move(s));
    }
    for (const auto& out : meta.outputs()) output_names->push_back(out.name());
    return true;
  }
  std::unique_ptr<tc::InferenceServerHttpClient> client;
  tc::Error e = tc::InferenceServerHttpClient::Create(&client, opt.url);
  if (!e.IsOk()) {
    *err = e.Message();
    return false;
  }
  std::string body;
  e = client->ModelMetadata(&body, opt.model);
  if (!e.IsOk()) {
    *err = e.Message();
    return false;
  }
  tc_tpu::json::Value doc;
  if (!tc_tpu::json::Parse(body, &doc, err)) return false;
  if (!doc.Has("inputs") || !doc.At("inputs").IsArray()) {
    *err = "model metadata carries no inputs array";
    return false;
  }
  for (const auto& in : doc.At("inputs").AsArray()) {
    TensorSpec s;
    s.name = in.At("name").AsString();
    s.datatype = in.At("datatype").AsString();
    for (const auto& d : in.At("shape").AsArray())
      s.dims.push_back(d.AsInt());
    specs->push_back(std::move(s));
  }
  if (doc.Has("outputs") && doc.At("outputs").IsArray()) {
    for (const auto& out : doc.At("outputs").AsArray())
      output_names->push_back(out.At("name").AsString());
  }
  return true;
}

}  // namespace

int
main(int argc, char** argv)
{
  Options opt;
  for (int i = 1; i < argc; ++i) {
    auto next = [&](const char* flag) -> const char* {
      if (i + 1 >= argc) {
        fprintf(stderr, "FAILED: %s needs a value\n", flag);
        exit(2);
      }
      return argv[++i];
    };
    if (!strcmp(argv[i], "-u")) {
      opt.url = next("-u");
    } else if (!strcmp(argv[i], "-i")) {
      opt.protocol = next("-i");
    } else if (!strcmp(argv[i], "-m")) {
      opt.model = next("-m");
    } else if (!strcmp(argv[i], "-b")) {
      opt.batch = atoi(next("-b"));
    } else if (!strcmp(argv[i], "-p")) {
      opt.window_ms = atoi(next("-p"));
    } else if (!strcmp(argv[i], "--warmup-ms")) {
      opt.warmup_ms = atoi(next("--warmup-ms"));
    } else if (!strcmp(argv[i], "--json")) {
      opt.json_out = true;
    } else if (!strcmp(argv[i], "--concurrency-range")) {
      double a, b, c;
      if (!ParseRange(next("--concurrency-range"), &a, &b, &c)) {
        fprintf(stderr, "FAILED: bad --concurrency-range\n");
        return 2;
      }
      opt.conc_start = static_cast<int>(a);
      opt.conc_end = static_cast<int>(b);
      opt.conc_step = static_cast<int>(c);
      if (opt.conc_start < 1 || opt.conc_step < 1 ||
          a != opt.conc_start || b != opt.conc_end || c != opt.conc_step) {
        // fractional or non-positive values truncate to a stuck or
        // zero-thread sweep — reject instead
        fprintf(stderr, "FAILED: --concurrency-range needs positive "
                        "integers\n");
        return 2;
      }
      opt.have_conc = true;
    } else if (!strcmp(argv[i], "--request-rate-range")) {
      if (!ParseRange(next("--request-rate-range"), &opt.rate_start,
                      &opt.rate_end, &opt.rate_step)) {
        fprintf(stderr, "FAILED: bad --request-rate-range\n");
        return 2;
      }
      opt.have_rate = true;
    } else if (!strcmp(argv[i], "--request-distribution")) {
      opt.distribution = next("--request-distribution");
      if (opt.distribution != "constant" && opt.distribution != "poisson") {
        fprintf(stderr, "FAILED: bad --request-distribution\n");
        return 2;
      }
    } else if (!strcmp(argv[i], "--max-threads")) {
      opt.max_threads = atoi(next("--max-threads"));
    } else if (!strcmp(argv[i], "--shared-memory")) {
      opt.shared_memory = next("--shared-memory");
      if (opt.shared_memory != "none" && opt.shared_memory != "system" &&
          opt.shared_memory != "xla") {
        fprintf(stderr, "FAILED: --shared-memory must be none|system|xla\n");
        return 2;
      }
    } else if (!strcmp(argv[i], "--output-shared-memory-size")) {
      long v = atol(next("--output-shared-memory-size"));
      if (v <= 0) {
        fprintf(stderr, "FAILED: bad --output-shared-memory-size\n");
        return 2;
      }
      opt.output_shm_size = static_cast<size_t>(v);
    } else {
      fprintf(stderr,
              "usage: %s -m MODEL [-u URL] [-i grpc|http] [-b BATCH] "
              "[-p WINDOW_MS] [--warmup-ms MS] [--json] "
              "[--concurrency-range S:E[:STEP]] "
              "[--request-rate-range S:E[:STEP] "
              "[--request-distribution constant|poisson]] "
              "[--max-threads N] [--shared-memory none|system|xla] "
              "[--output-shared-memory-size BYTES]\n",
              argv[0]);
      return 2;
    }
  }
  if (opt.model.empty()) {
    fprintf(stderr, "FAILED: -m MODEL is required\n");
    return 2;
  }
  if (opt.protocol != "grpc" && opt.protocol != "http") {
    fprintf(stderr, "FAILED: -i must be grpc or http\n");
    return 2;
  }
  if (opt.have_rate && opt.rate_start <= 0) {
    fprintf(stderr, "FAILED: request rate must be > 0\n");
    return 2;
  }
  if (!opt.have_conc && !opt.have_rate) opt.have_conc = true;

  std::vector<TensorSpec> specs;
  std::vector<std::string> output_names;
  std::string err;
  if (!FetchSpecs(opt, &specs, &output_names, &err)) {
    fprintf(stderr, "FAILED: model metadata: %s\n", err.c_str());
    return 1;
  }
  if (specs.empty()) {
    fprintf(stderr, "FAILED: model has no inputs\n");
    return 1;
  }
  for (const auto& s : specs) {
    if (s.datatype != "BYTES" && DtypeSize(s.datatype) == 0) {
      fprintf(stderr, "FAILED: unsupported input datatype %s\n",
              s.datatype.c_str());
      return 1;
    }
    if (s.datatype == "BYTES" && opt.shared_memory != "none") {
      fprintf(stderr,
              "FAILED: BYTES inputs cannot ride --shared-memory\n");
      return 1;
    }
  }
  Workload wl(opt, std::move(specs), std::move(output_names));
  int rc = 0;
  if (opt.have_conc) rc = RunClosedLoop(opt, &wl);
  if (rc == 0 && opt.have_rate) rc = RunOpenLoop(opt, &wl);
  if (rc == 0) printf("PASS: perf_client\n");
  return rc;
}
