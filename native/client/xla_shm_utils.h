// C++ XLA shared-memory helpers — the TPU device-side data path for native
// clients.
//
// Parity targets: reference ipc.h:28-32 (opaque cudaIpcMemHandle_t kept out
// of the ABI when GPU is off) and the cudaIPC client flow in
// http_client.cc:1708-1748 / examples/simple_grpc_cudashm_client.py (create
// region -> register raw handle -> set inputs -> infer via region names ->
// read outputs -> unregister/destroy).
//
// TPU translation (same design as the Python xla_shared_memory module,
// triton_client_tpu/utils/xla_shared_memory/__init__.py): PjRt buffers are
// not cross-process importable the way cudaIpcOpenMemHandle is, so the
// portable raw handle is a JSON descriptor naming a POSIX host-shm *staging*
// region; the server imports it and pays exactly one host<->device DMA per
// direction.  In-process Python clients instead share a live device slot —
// a C++ client is by definition out of process, so it always takes the
// staging path.
#pragma once

#include <cstddef>
#include <cstdint>
#include <string>
#include <vector>

#include "common.h"

namespace tc_tpu {
namespace client {

// Opaque region handle (ipc.h analog): owns the mmap'd staging region plus
// an 8-byte generation counter the server uses to cache its device import
// (unchanged region -> the server skips the host copy AND the DMA on every
// subsequent infer — the TPU analog of cudaIPC's map-once read path).
struct XlaShmHandle {
  std::string triton_shm_name;  // registration name
  std::string staging_key;      // POSIX shm key ("/xlashm_...")
  std::string seq_key;          // generation-counter shm key
  std::string uuid;             // slot id (never resolves cross-process)
  size_t byte_size = 0;
  int device_id = 0;
  void* base_addr = nullptr;
  void* seq_addr = nullptr;
  int shm_fd = -1;
  int seq_fd = -1;
};

// Allocate the staging region + descriptor for a device-backed region
// (reference cuda_shared_memory.create_shared_memory_region).
Error CreateXlaSharedMemoryRegion(
    XlaShmHandle* handle, const std::string& triton_shm_name,
    size_t byte_size, int device_id);

// Serialized import descriptor to pass to Register{Cuda,Xla}SharedMemory
// (reference cuda_shared_memory.get_raw_handle: base64 of
// cudaIpcMemHandle.reserved; here a JSON descriptor both registries parse).
Error GetXlaSharedMemoryRawHandle(
    const XlaShmHandle& handle, std::vector<uint8_t>* raw_handle);

// Write bytes into the region (reference set_shared_memory_region:
// cudaMemcpyAsync + sync; here a memcpy into staging — the server's
// device_put is the H2D).
Error SetXlaSharedMemoryRegion(
    const XlaShmHandle& handle, const void* data, size_t byte_size,
    size_t offset = 0);

// Read bytes back (reference get_contents_as_numpy D2H path).
Error GetXlaSharedMemoryContents(
    const XlaShmHandle& handle, void* out, size_t byte_size,
    size_t offset = 0);

// Zero-copy write path: build tensor data DIRECTLY in the mapped region
// (no client-side memcpy), then Commit to publish — bumps the generation
// counter so the server re-imports exactly once and serves every further
// infer from its cached device array.
Error XlaSharedMemoryData(
    const XlaShmHandle& handle, void** data, size_t offset = 0);
Error CommitXlaSharedMemoryRegion(const XlaShmHandle& handle);

// Unmap + unlink the staging region (reference destroy_shared_memory_region
// / cudaFree in CudaSharedMemoryRegion.__del__).
Error DestroyXlaSharedMemoryRegion(XlaShmHandle* handle);

}  // namespace client
}  // namespace tc_tpu
