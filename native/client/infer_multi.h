// Shared multi-request fan-out (reference InferMulti/AsyncInferMulti,
// http_client.cc:1911-2021): the broadcast-arity rules, the error-cleanup
// loop, and the atomic countdown join are identical for the HTTP and gRPC
// clients, so they live once here and each client instantiates them with
// its own Infer/AsyncInfer callable.
#pragma once

#include <atomic>
#include <functional>
#include <memory>
#include <string>
#include <vector>

#include "common.h"

namespace tc_tpu {
namespace client {
namespace multi_detail {

template <typename T>
inline Error CheckMultiArity(
    const std::vector<T>& v, size_t n, const char* what) {
  if (v.size() == 1 || v.size() == n) return Error::Success;
  return Error(
      std::string("expected 1 or ") + std::to_string(n) + " " + what +
      ", got " + std::to_string(v.size()));
}

// infer_fn(result_out, options, inputs, outputs) -> Error
template <typename InferFn>
Error InferMultiImpl(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    InferFn&& infer_fn) {
  const size_t n = inputs.size();
  if (n == 0) return Error("no inference requests provided");
  TC_RETURN_IF_ERROR(CheckMultiArity(options, n, "options"));
  if (!outputs.empty()) {
    TC_RETURN_IF_ERROR(CheckMultiArity(outputs, n, "outputs"));
  }
  results->clear();
  results->reserve(n);
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < n; ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    InferResult* result = nullptr;
    Error err = infer_fn(&result, opt, inputs[i], outs);
    if (!err.IsOk()) {
      for (InferResult* r : *results) delete r;
      results->clear();
      return err;
    }
    results->push_back(result);
  }
  return Error::Success;
}

// async_fn(per_request_callback, options, inputs, outputs) -> Error.
// The user callback fires once, with results in request order, after the
// last request completes (atomic countdown join).
template <typename AsyncFn>
Error AsyncInferMultiImpl(
    std::function<void(std::vector<InferResult*>)> callback,
    const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    AsyncFn&& async_fn) {
  const size_t n = inputs.size();
  if (n == 0) return Error("no inference requests provided");
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInferMulti");
  }
  TC_RETURN_IF_ERROR(CheckMultiArity(options, n, "options"));
  if (!outputs.empty()) {
    TC_RETURN_IF_ERROR(CheckMultiArity(outputs, n, "outputs"));
  }
  struct MultiState {
    std::function<void(std::vector<InferResult*>)> callback;
    std::vector<InferResult*> results;
    std::atomic<size_t> remaining;
  };
  auto state = std::make_shared<MultiState>();
  state->callback = std::move(callback);
  state->results.resize(n, nullptr);
  state->remaining = n;
  static const std::vector<const InferRequestedOutput*> kNoOutputs;
  for (size_t i = 0; i < n; ++i) {
    const InferOptions& opt = options.size() == 1 ? options[0] : options[i];
    const auto& outs = outputs.empty()
                           ? kNoOutputs
                           : (outputs.size() == 1 ? outputs[0] : outputs[i]);
    Error err = async_fn(
        [state, i](InferResult* result) {
          state->results[i] = result;
          if (state->remaining.fetch_sub(1) == 1) {
            state->callback(std::move(state->results));
          }
        },
        opt, inputs[i], outs);
    if (!err.IsOk()) {
      // deliver the submit failure through the slot so the join still fires
      state->results[i] = new ErrorResult(err);
      if (state->remaining.fetch_sub(1) == 1) {
        state->callback(std::move(state->results));
      }
    }
  }
  return Error::Success;
}

}  // namespace multi_detail
}  // namespace client
}  // namespace tc_tpu
