#include "json.h"

#include <cctype>
#include <cmath>
#include <cstdio>
#include <cstring>

namespace tc_tpu {
namespace json {

namespace {
const Value kNullValue;

struct Parser {
  const char* p;
  const char* end;
  std::string* err;

  bool Fail(const std::string& msg) {
    if (err) *err = msg;
    return false;
  }

  void SkipWs() {
    while (p < end && (*p == ' ' || *p == '\t' || *p == '\n' || *p == '\r')) ++p;
  }

  bool ParseValue(Value* out, int depth) {
    if (depth > 128) return Fail("nesting too deep");
    SkipWs();
    if (p >= end) return Fail("unexpected end of input");
    switch (*p) {
      case '{':
        return ParseObject(out, depth);
      case '[':
        return ParseArray(out, depth);
      case '"': {
        std::string s;
        if (!ParseString(&s)) return false;
        *out = Value(std::move(s));
        return true;
      }
      case 't':
        if (end - p >= 4 && strncmp(p, "true", 4) == 0) {
          p += 4;
          *out = Value(true);
          return true;
        }
        return Fail("invalid literal");
      case 'f':
        if (end - p >= 5 && strncmp(p, "false", 5) == 0) {
          p += 5;
          *out = Value(false);
          return true;
        }
        return Fail("invalid literal");
      case 'n':
        if (end - p >= 4 && strncmp(p, "null", 4) == 0) {
          p += 4;
          *out = Value();
          return true;
        }
        return Fail("invalid literal");
      default:
        return ParseNumber(out);
    }
  }

  bool ParseObject(Value* out, int depth) {
    ++p;  // '{'
    Object obj;
    SkipWs();
    if (p < end && *p == '}') {
      ++p;
      *out = Value(std::move(obj));
      return true;
    }
    while (true) {
      SkipWs();
      if (p >= end || *p != '"') return Fail("expected object key");
      std::string key;
      if (!ParseString(&key)) return false;
      SkipWs();
      if (p >= end || *p != ':') return Fail("expected ':'");
      ++p;
      Value v;
      if (!ParseValue(&v, depth + 1)) return false;
      obj.emplace(std::move(key), std::move(v));
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == '}') {
        ++p;
        *out = Value(std::move(obj));
        return true;
      }
      return Fail("expected ',' or '}'");
    }
  }

  bool ParseArray(Value* out, int depth) {
    ++p;  // '['
    Array arr;
    SkipWs();
    if (p < end && *p == ']') {
      ++p;
      *out = Value(std::move(arr));
      return true;
    }
    while (true) {
      Value v;
      if (!ParseValue(&v, depth + 1)) return false;
      arr.push_back(std::move(v));
      SkipWs();
      if (p < end && *p == ',') {
        ++p;
        continue;
      }
      if (p < end && *p == ']') {
        ++p;
        *out = Value(std::move(arr));
        return true;
      }
      return Fail("expected ',' or ']'");
    }
  }

  bool ParseString(std::string* out) {
    ++p;  // opening quote
    std::string s;
    while (p < end) {
      unsigned char c = static_cast<unsigned char>(*p);
      if (c == '"') {
        ++p;
        *out = std::move(s);
        return true;
      }
      if (c == '\\') {
        ++p;
        if (p >= end) return Fail("bad escape");
        switch (*p) {
          case '"': s += '"'; break;
          case '\\': s += '\\'; break;
          case '/': s += '/'; break;
          case 'b': s += '\b'; break;
          case 'f': s += '\f'; break;
          case 'n': s += '\n'; break;
          case 'r': s += '\r'; break;
          case 't': s += '\t'; break;
          case 'u': {
            if (end - p < 5) return Fail("bad \\u escape");
            unsigned int cp = 0;
            for (int i = 1; i <= 4; ++i) {
              char h = p[i];
              cp <<= 4;
              if (h >= '0' && h <= '9') cp |= h - '0';
              else if (h >= 'a' && h <= 'f') cp |= h - 'a' + 10;
              else if (h >= 'A' && h <= 'F') cp |= h - 'A' + 10;
              else return Fail("bad \\u escape");
            }
            p += 4;
            // encode UTF-8 (surrogate pairs for completeness)
            if (cp >= 0xD800 && cp <= 0xDBFF && end - p >= 7 && p[1] == '\\' &&
                p[2] == 'u') {
              unsigned int lo = 0;
              bool ok = true;
              for (int i = 3; i <= 6; ++i) {
                char h = p[i];
                lo <<= 4;
                if (h >= '0' && h <= '9') lo |= h - '0';
                else if (h >= 'a' && h <= 'f') lo |= h - 'a' + 10;
                else if (h >= 'A' && h <= 'F') lo |= h - 'A' + 10;
                else { ok = false; break; }
              }
              if (ok && lo >= 0xDC00 && lo <= 0xDFFF) {
                cp = 0x10000 + ((cp - 0xD800) << 10) + (lo - 0xDC00);
                p += 6;
              }
            }
            if (cp < 0x80) {
              s += static_cast<char>(cp);
            } else if (cp < 0x800) {
              s += static_cast<char>(0xC0 | (cp >> 6));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            } else if (cp < 0x10000) {
              s += static_cast<char>(0xE0 | (cp >> 12));
              s += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            } else {
              s += static_cast<char>(0xF0 | (cp >> 18));
              s += static_cast<char>(0x80 | ((cp >> 12) & 0x3F));
              s += static_cast<char>(0x80 | ((cp >> 6) & 0x3F));
              s += static_cast<char>(0x80 | (cp & 0x3F));
            }
            break;
          }
          default:
            return Fail("bad escape");
        }
        ++p;
      } else {
        s += static_cast<char>(c);
        ++p;
      }
    }
    return Fail("unterminated string");
  }

  bool ParseNumber(Value* out) {
    const char* start = p;
    if (p < end && (*p == '-' || *p == '+')) ++p;
    bool is_double = false;
    while (p < end && (isdigit(static_cast<unsigned char>(*p)) || *p == '.' ||
                       *p == 'e' || *p == 'E' || *p == '-' || *p == '+')) {
      if (*p == '.' || *p == 'e' || *p == 'E') is_double = true;
      ++p;
    }
    if (p == start) return Fail("invalid number");
    std::string num(start, p - start);
    if (is_double) {
      *out = Value(strtod(num.c_str(), nullptr));
    } else {
      *out = Value(static_cast<int64_t>(strtoll(num.c_str(), nullptr, 10)));
    }
    return true;
  }
};

void Escape(const std::string& s, std::string* out) {
  out->push_back('"');
  for (unsigned char c : s) {
    switch (c) {
      case '"': *out += "\\\""; break;
      case '\\': *out += "\\\\"; break;
      case '\b': *out += "\\b"; break;
      case '\f': *out += "\\f"; break;
      case '\n': *out += "\\n"; break;
      case '\r': *out += "\\r"; break;
      case '\t': *out += "\\t"; break;
      default:
        if (c < 0x20) {
          char buf[8];
          snprintf(buf, sizeof(buf), "\\u%04x", c);
          *out += buf;
        } else {
          out->push_back(static_cast<char>(c));
        }
    }
  }
  out->push_back('"');
}

void SerializeTo(const Value& v, std::string* out) {
  switch (v.type()) {
    case Value::Type::kNull: *out += "null"; break;
    case Value::Type::kBool: *out += v.AsBool() ? "true" : "false"; break;
    case Value::Type::kInt: {
      char buf[32];
      snprintf(buf, sizeof(buf), "%lld", static_cast<long long>(v.AsInt()));
      *out += buf;
      break;
    }
    case Value::Type::kDouble: {
      double d = v.AsDouble();
      char buf[40];
      if (std::isfinite(d)) {
        snprintf(buf, sizeof(buf), "%.17g", d);
        *out += buf;
      } else {
        *out += "null";  // JSON has no inf/nan
      }
      break;
    }
    case Value::Type::kString: Escape(v.AsString(), out); break;
    case Value::Type::kArray: {
      out->push_back('[');
      bool first = true;
      for (const auto& e : v.AsArray()) {
        if (!first) out->push_back(',');
        first = false;
        SerializeTo(e, out);
      }
      out->push_back(']');
      break;
    }
    case Value::Type::kObject: {
      out->push_back('{');
      bool first = true;
      for (const auto& kv : v.AsObject()) {
        if (!first) out->push_back(',');
        first = false;
        Escape(kv.first, out);
        out->push_back(':');
        SerializeTo(kv.second, out);
      }
      out->push_back('}');
      break;
    }
  }
}

}  // namespace

const Value& Value::At(const std::string& key) const {
  if (type_ == Type::kObject) {
    auto it = object_.find(key);
    if (it != object_.end()) return it->second;
  }
  return kNullValue;
}

std::string Value::Serialize() const {
  std::string out;
  SerializeTo(*this, &out);
  return out;
}

bool Parse(const char* data, size_t size, Value* out, std::string* err) {
  Parser parser{data, data + size, err};
  if (!parser.ParseValue(out, 0)) return false;
  parser.SkipWs();
  if (parser.p != parser.end) {
    if (err) *err = "trailing characters after JSON document";
    return false;
  }
  return true;
}

bool Parse(const std::string& s, Value* out, std::string* err) {
  return Parse(s.data(), s.size(), out, err);
}

}  // namespace json
}  // namespace tc_tpu
