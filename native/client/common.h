// C++ client common core.
//
// Parity target: reference src/c++/library/common.h (676 LoC) — same public
// classes and semantics: Error value type (:61-83), InferOptions (:164-231),
// InferInput with a scatter-gather buffer list (:282-369), BYTES
// serialization <u32 len><chars> (common.cc:169-183), shm binding state
// machine IOType{NONE,RAW,SHARED_MEMORY} (:388-392), InferRequestedOutput
// (:400-482), abstract InferResult incl. decoupled final/null response
// queries (:488-563), RequestTimers 6-point nanosecond timestamps
// (:568-648), InferStat accounting (:93-114).
//
// Re-designed, not ported: no CUDA types — the device data path registers
// XLA buffers by handle (see xla shm registries); transports are
// socket-based (http_client.h) and gRPC-Web framed (grpc_client.h).
#pragma once

#include <chrono>
#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <vector>

namespace tc_tpu {
namespace client {

//==============================================================================
class Error {
 public:
  Error() : has_error_(false) {}
  explicit Error(const std::string& msg) : has_error_(true), msg_(msg) {}

  static const Error Success;

  bool IsOk() const { return !has_error_; }
  const std::string& Message() const { return msg_; }

  friend std::ostream& operator<<(std::ostream&, const Error&);

 private:
  bool has_error_;
  std::string msg_;
};

#define TC_RETURN_IF_ERROR(expr)          \
  do {                                    \
    const tc_tpu::client::Error err__ = (expr); \
    if (!err__.IsOk()) return err__;      \
  } while (false)

//==============================================================================
// Request options (reference common.h:164-231).
struct InferOptions {
  explicit InferOptions(const std::string& model_name)
      : model_name_(model_name) {}

  std::string model_name_;
  std::string model_version_;
  std::string request_id_;
  uint64_t sequence_id_ = 0;
  std::string sequence_id_str_;  // string correlation id (dyna sequences)
  bool sequence_start_ = false;
  bool sequence_end_ = false;
  uint64_t priority_ = 0;
  uint64_t server_timeout_us_ = 0;  // request timeout forwarded to server
  uint64_t client_timeout_us_ = 0;  // client-side deadline
  bool triton_enable_empty_final_response_ = false;
  std::map<std::string, std::string> request_parameters_;
};

//==============================================================================
// Input tensor with scatter-gather data references (reference
// common.h:282-369: AppendRaw keeps caller pointers; GetNext streams
// chunks so transports copy at most once).
class InferInput {
 public:
  enum class IOType { kNone, kRaw, kSharedMemory };

  static Error Create(
      InferInput** infer_input, const std::string& name,
      const std::vector<int64_t>& dims, const std::string& datatype);

  const std::string& Name() const { return name_; }
  const std::string& Datatype() const { return datatype_; }
  const std::vector<int64_t>& Shape() const { return shape_; }
  Error SetShape(const std::vector<int64_t>& dims);

  // Append a raw chunk; the caller keeps the buffer alive until the request
  // completes (zero-copy into the transport).
  Error AppendRaw(const uint8_t* input, size_t input_byte_size);
  Error AppendRaw(const std::vector<uint8_t>& input);
  // BYTES tensors: serialize <u32 len><bytes> per element.
  Error AppendFromString(const std::vector<std::string>& input);

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error Reset();

  // Scatter-gather iteration for transports.
  size_t TotalByteSize() const { return total_byte_size_; }
  void PrepareForRequest() const;
  // Copy up to size bytes into buf; *input_bytes = copied, *end_of_input set
  // when the gather list is exhausted (curl-style provider).
  Error GetNext(uint8_t* buf, size_t size, size_t* input_bytes,
                bool* end_of_input) const;
  // Zero-copy chunk access (grpc-style).
  Error GetNext(const uint8_t** buf, size_t* input_bytes,
                bool* end_of_input) const;

  IOType Type() const { return io_type_; }
  const std::string& SharedMemoryRegion() const { return shm_region_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  InferInput(const std::string& name, const std::vector<int64_t>& dims,
             const std::string& datatype);

  std::string name_;
  std::vector<int64_t> shape_;
  std::string datatype_;
  IOType io_type_ = IOType::kNone;

  std::vector<std::pair<const uint8_t*, size_t>> bufs_;
  std::vector<std::string> owned_;  // storage for serialized BYTES payloads
  size_t total_byte_size_ = 0;
  mutable size_t gather_index_ = 0;
  mutable size_t gather_offset_ = 0;

  std::string shm_region_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// Requested output (reference common.h:400-482).
class InferRequestedOutput {
 public:
  static Error Create(
      InferRequestedOutput** infer_output, const std::string& name,
      size_t class_count = 0);

  const std::string& Name() const { return name_; }
  size_t ClassCount() const { return class_count_; }

  Error SetSharedMemory(
      const std::string& region_name, size_t byte_size, size_t offset = 0);
  Error UnsetSharedMemory();

  bool IsSharedMemory() const { return is_shm_; }
  const std::string& SharedMemoryRegion() const { return shm_region_; }
  size_t SharedMemoryByteSize() const { return shm_byte_size_; }
  size_t SharedMemoryOffset() const { return shm_offset_; }

 private:
  InferRequestedOutput(const std::string& name, size_t class_count)
      : name_(name), class_count_(class_count) {}

  std::string name_;
  size_t class_count_;
  bool is_shm_ = false;
  std::string shm_region_;
  size_t shm_byte_size_ = 0;
  size_t shm_offset_ = 0;
};

//==============================================================================
// Abstract inference result (reference common.h:488-563).
class InferResult {
 public:
  virtual ~InferResult() = default;

  virtual Error ModelName(std::string* name) const = 0;
  virtual Error ModelVersion(std::string* version) const = 0;
  virtual Error Id(std::string* id) const = 0;
  virtual Error Shape(
      const std::string& output_name, std::vector<int64_t>* shape) const = 0;
  virtual Error Datatype(
      const std::string& output_name, std::string* datatype) const = 0;
  virtual Error RawData(
      const std::string& output_name, const uint8_t** buf,
      size_t* byte_size) const = 0;
  // BYTES output -> vector of strings (reference StringData).
  virtual Error StringData(
      const std::string& output_name,
      std::vector<std::string>* string_result) const;
  virtual Error IsFinalResponse(bool* is_final_response) const;
  virtual Error IsNullResponse(bool* is_null_response) const;
  virtual Error RequestStatus() const = 0;
  virtual std::string DebugString() const = 0;
};

// Result carrying only an error — delivered to async/stream callbacks when
// the request itself failed, so callbacks always receive an InferResult.
class ErrorResult : public InferResult {
 public:
  explicit ErrorResult(Error e) : err_(std::move(e)) {}
  Error ModelName(std::string*) const override { return err_; }
  Error ModelVersion(std::string*) const override { return err_; }
  Error Id(std::string*) const override { return err_; }
  Error Shape(const std::string&, std::vector<int64_t>*) const override {
    return err_;
  }
  Error Datatype(const std::string&, std::string*) const override {
    return err_;
  }
  Error RawData(const std::string&, const uint8_t**, size_t*) const override {
    return err_;
  }
  Error RequestStatus() const override { return err_; }
  std::string DebugString() const override { return err_.Message(); }

 private:
  Error err_;
};

//==============================================================================
// Six-point request timers (reference common.h:568-648).
class RequestTimers {
 public:
  enum class Kind : int {
    REQUEST_START = 0,
    REQUEST_END = 1,
    SEND_START = 2,
    SEND_END = 3,
    RECV_START = 4,
    RECV_END = 5,
    COUNT__ = 6,
  };

  RequestTimers() { Reset(); }
  void Reset() {
    for (auto& t : timestamps_) t = 0;
  }
  void CaptureTimestamp(Kind kind) {
    timestamps_[static_cast<int>(kind)] =
        std::chrono::duration_cast<std::chrono::nanoseconds>(
            std::chrono::steady_clock::now().time_since_epoch())
            .count();
  }
  uint64_t Timestamp(Kind kind) const {
    return timestamps_[static_cast<int>(kind)];
  }
  uint64_t Duration(Kind start, Kind end) const {
    uint64_t s = Timestamp(start), e = Timestamp(end);
    return (s == 0 || e == 0 || e < s) ? 0 : e - s;
  }

 private:
  uint64_t timestamps_[static_cast<int>(Kind::COUNT__)];
};

//==============================================================================
// Cumulative client-side statistics (reference common.h:93-114).
struct InferStat {
  size_t completed_request_count = 0;
  uint64_t cumulative_total_request_time_ns = 0;
  uint64_t cumulative_send_time_ns = 0;
  uint64_t cumulative_receive_time_ns = 0;
};

//==============================================================================
// Base client: stat accounting shared by both transports (reference
// common.h:119-153; the worker thread lives in each transport).
class InferenceServerClient {
 public:
  explicit InferenceServerClient(bool verbose) : verbose_(verbose) {}
  virtual ~InferenceServerClient() = default;

  Error ClientInferStat(InferStat* infer_stat) const {
    std::lock_guard<std::mutex> lk(stat_mu_);
    *infer_stat = infer_stat_;
    return Error::Success;
  }

 protected:
  // Thread-safe: concurrent Infer() callers (async workers, multiplexed
  // unary calls) all account into one InferStat.
  void UpdateInferStat(const RequestTimers& timer);

  bool verbose_;
  mutable std::mutex stat_mu_;
  InferStat infer_stat_;
};

// BYTES wire helpers (reference common.cc:169-183 / utils __init__.py:193).
void SerializeStringTensor(
    const std::vector<std::string>& strings, std::string* out);
Error DeserializeStringTensor(
    const uint8_t* data, size_t size, std::vector<std::string>* out);

}  // namespace client
}  // namespace tc_tpu
