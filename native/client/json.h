// Minimal JSON value/parser/writer for the C++ client library.
//
// The image ships no rapidjson/nlohmann headers, so the client carries its
// own ~300-line JSON layer (the reference wraps rapidjson via
// src/c++/library/json_utils.h:37; same role here, zero dependencies).
#pragma once

#include <cstdint>
#include <map>
#include <memory>
#include <string>
#include <vector>

namespace tc_tpu {
namespace json {

class Value;
using Object = std::map<std::string, Value>;
using Array = std::vector<Value>;

class Value {
 public:
  enum class Type { kNull, kBool, kInt, kDouble, kString, kArray, kObject };

  Value() : type_(Type::kNull) {}
  Value(bool b) : type_(Type::kBool), bool_(b) {}
  Value(int64_t i) : type_(Type::kInt), int_(i) {}
  Value(int i) : type_(Type::kInt), int_(i) {}
  Value(uint64_t u) : type_(Type::kInt), int_(static_cast<int64_t>(u)) {}
  Value(double d) : type_(Type::kDouble), double_(d) {}
  Value(const char* s) : type_(Type::kString), string_(s) {}
  Value(std::string s) : type_(Type::kString), string_(std::move(s)) {}
  Value(Array a) : type_(Type::kArray), array_(std::move(a)) {}
  Value(Object o) : type_(Type::kObject), object_(std::move(o)) {}

  Type type() const { return type_; }
  bool IsNull() const { return type_ == Type::kNull; }
  bool IsBool() const { return type_ == Type::kBool; }
  bool IsNumber() const { return type_ == Type::kInt || type_ == Type::kDouble; }
  bool IsInt() const { return type_ == Type::kInt; }
  bool IsString() const { return type_ == Type::kString; }
  bool IsArray() const { return type_ == Type::kArray; }
  bool IsObject() const { return type_ == Type::kObject; }

  bool AsBool() const { return bool_; }
  int64_t AsInt() const {
    return type_ == Type::kDouble ? static_cast<int64_t>(double_) : int_;
  }
  double AsDouble() const {
    return type_ == Type::kInt ? static_cast<double>(int_) : double_;
  }
  const std::string& AsString() const { return string_; }
  const Array& AsArray() const { return array_; }
  Array& AsArray() { return array_; }
  const Object& AsObject() const { return object_; }
  Object& AsObject() { return object_; }

  // object helpers
  bool Has(const std::string& key) const {
    return type_ == Type::kObject && object_.count(key) > 0;
  }
  const Value& At(const std::string& key) const;  // null value if missing

  std::string Serialize() const;

 private:
  Type type_;
  bool bool_ = false;
  int64_t int_ = 0;
  double double_ = 0.0;
  std::string string_;
  Array array_;
  Object object_;
};

// Parse a JSON document from [data, data+size). Returns true on success;
// on failure fills *err with a position-tagged message.
bool Parse(const char* data, size_t size, Value* out, std::string* err);
bool Parse(const std::string& s, Value* out, std::string* err);

}  // namespace json
}  // namespace tc_tpu
