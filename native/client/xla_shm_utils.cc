#include "xla_shm_utils.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <random>

namespace tc_tpu {
namespace client {

namespace {

std::string RandomHex(size_t n_chars) {
  static const char hex[] = "0123456789abcdef";
  std::random_device rd;
  std::mt19937_64 gen(rd());
  std::uniform_int_distribution<int> dist(0, 15);
  std::string out;
  out.reserve(n_chars);
  for (size_t i = 0; i < n_chars; ++i) out += hex[dist(gen)];
  return out;
}

}  // namespace

Error CreateXlaSharedMemoryRegion(
    XlaShmHandle* handle, const std::string& triton_shm_name,
    size_t byte_size, int device_id) {
  if (byte_size == 0) {
    return Error("byte_size must be positive");
  }
  handle->triton_shm_name = triton_shm_name;
  handle->uuid = RandomHex(32);
  handle->staging_key = "/xlashm_" + handle->uuid.substr(0, 16);
  handle->byte_size = byte_size;
  handle->device_id = device_id;

  int fd = ::shm_open(
      handle->staging_key.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return Error(
        "failed to create staging region '" + handle->staging_key + "': " +
        strerror(errno));
  }
  if (::ftruncate(fd, static_cast<off_t>(byte_size)) != 0) {
    Error err(
        "failed to size staging region '" + handle->staging_key + "': " +
        strerror(errno));
    ::close(fd);
    ::shm_unlink(handle->staging_key.c_str());
    return err;
  }
  void* base = ::mmap(
      nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    Error err(
        "failed to map staging region '" + handle->staging_key + "': " +
        strerror(errno));
    ::close(fd);
    ::shm_unlink(handle->staging_key.c_str());
    return err;
  }
  handle->shm_fd = fd;
  handle->base_addr = base;

  // generation counter (8 bytes): bumped on every write/commit so the
  // server's import cache knows when to re-read the staging bytes
  handle->seq_key = handle->staging_key + "_seq";
  int sfd = ::shm_open(
      handle->seq_key.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (sfd < 0 || ::ftruncate(sfd, 8) != 0) {
    Error err(
        "failed to create seq region '" + handle->seq_key + "': " +
        strerror(errno));
    if (sfd >= 0) ::close(sfd);
    ::shm_unlink(handle->seq_key.c_str());
    DestroyXlaSharedMemoryRegion(handle);
    return err;
  }
  void* sbase = ::mmap(nullptr, 8, PROT_READ | PROT_WRITE, MAP_SHARED,
                       sfd, 0);
  if (sbase == MAP_FAILED) {
    Error err(
        "failed to map seq region '" + handle->seq_key + "': " +
        strerror(errno));
    ::close(sfd);
    ::shm_unlink(handle->seq_key.c_str());
    DestroyXlaSharedMemoryRegion(handle);
    return err;
  }
  handle->seq_fd = sfd;
  handle->seq_addr = sbase;
  *static_cast<uint64_t*>(sbase) = 0;
  return Error::Success;
}

Error GetXlaSharedMemoryRawHandle(
    const XlaShmHandle& handle, std::vector<uint8_t>* raw_handle) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  char buf[384];
  int n = snprintf(
      buf, sizeof(buf),
      "{\"uuid\": \"%s\", \"staging_key\": \"%s\", \"seq_key\": \"%s\", "
      "\"byte_size\": %zu, \"device_id\": %d}",
      handle.uuid.c_str(), handle.staging_key.c_str(),
      handle.seq_key.c_str(), handle.byte_size, handle.device_id);
  raw_handle->assign(buf, buf + n);
  return Error::Success;
}

Error SetXlaSharedMemoryRegion(
    const XlaShmHandle& handle, const void* data, size_t byte_size,
    size_t offset) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  // overflow-safe bounds check: offset + byte_size could wrap size_t
  if (offset > handle.byte_size || byte_size > handle.byte_size - offset) {
    return Error(
        "write of " + std::to_string(byte_size) + " bytes at offset " +
        std::to_string(offset) + " exceeds region size " +
        std::to_string(handle.byte_size));
  }
  memcpy(static_cast<uint8_t*>(handle.base_addr) + offset, data, byte_size);
  return CommitXlaSharedMemoryRegion(handle);
}

Error XlaSharedMemoryData(
    const XlaShmHandle& handle, void** data, size_t offset) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  if (offset >= handle.byte_size) {
    return Error(
        "offset " + std::to_string(offset) + " exceeds region size " +
        std::to_string(handle.byte_size));
  }
  *data = static_cast<uint8_t*>(handle.base_addr) + offset;
  return Error::Success;
}

Error CommitXlaSharedMemoryRegion(const XlaShmHandle& handle) {
  if (handle.seq_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  __atomic_fetch_add(static_cast<uint64_t*>(handle.seq_addr), 1,
                     __ATOMIC_SEQ_CST);
  return Error::Success;
}

Error GetXlaSharedMemoryContents(
    const XlaShmHandle& handle, void* out, size_t byte_size, size_t offset) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  if (offset > handle.byte_size || byte_size > handle.byte_size - offset) {
    return Error(
        "read of " + std::to_string(byte_size) + " bytes at offset " +
        std::to_string(offset) + " exceeds region size " +
        std::to_string(handle.byte_size));
  }
  memcpy(out, static_cast<const uint8_t*>(handle.base_addr) + offset,
         byte_size);
  return Error::Success;
}

Error DestroyXlaSharedMemoryRegion(XlaShmHandle* handle) {
  if (handle->base_addr != nullptr) {
    ::munmap(handle->base_addr, handle->byte_size);
    handle->base_addr = nullptr;
  }
  if (handle->shm_fd >= 0) {
    ::close(handle->shm_fd);
    handle->shm_fd = -1;
  }
  if (!handle->staging_key.empty()) {
    ::shm_unlink(handle->staging_key.c_str());
    handle->staging_key.clear();
  }
  if (handle->seq_addr != nullptr) {
    ::munmap(handle->seq_addr, 8);
    handle->seq_addr = nullptr;
  }
  if (handle->seq_fd >= 0) {
    ::close(handle->seq_fd);
    handle->seq_fd = -1;
  }
  if (!handle->seq_key.empty()) {
    ::shm_unlink(handle->seq_key.c_str());
    handle->seq_key.clear();
  }
  return Error::Success;
}

}  // namespace client
}  // namespace tc_tpu
