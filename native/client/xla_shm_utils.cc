#include "xla_shm_utils.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <cstdio>
#include <cstring>
#include <random>

namespace tc_tpu {
namespace client {

namespace {

std::string RandomHex(size_t n_chars) {
  static const char hex[] = "0123456789abcdef";
  std::random_device rd;
  std::mt19937_64 gen(rd());
  std::uniform_int_distribution<int> dist(0, 15);
  std::string out;
  out.reserve(n_chars);
  for (size_t i = 0; i < n_chars; ++i) out += hex[dist(gen)];
  return out;
}

}  // namespace

Error CreateXlaSharedMemoryRegion(
    XlaShmHandle* handle, const std::string& triton_shm_name,
    size_t byte_size, int device_id) {
  if (byte_size == 0) {
    return Error("byte_size must be positive");
  }
  handle->triton_shm_name = triton_shm_name;
  handle->uuid = RandomHex(32);
  handle->staging_key = "/xlashm_" + handle->uuid.substr(0, 16);
  handle->byte_size = byte_size;
  handle->device_id = device_id;

  int fd = ::shm_open(
      handle->staging_key.c_str(), O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    return Error(
        "failed to create staging region '" + handle->staging_key + "': " +
        strerror(errno));
  }
  if (::ftruncate(fd, static_cast<off_t>(byte_size)) != 0) {
    Error err(
        "failed to size staging region '" + handle->staging_key + "': " +
        strerror(errno));
    ::close(fd);
    ::shm_unlink(handle->staging_key.c_str());
    return err;
  }
  void* base = ::mmap(
      nullptr, byte_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (base == MAP_FAILED) {
    Error err(
        "failed to map staging region '" + handle->staging_key + "': " +
        strerror(errno));
    ::close(fd);
    ::shm_unlink(handle->staging_key.c_str());
    return err;
  }
  handle->shm_fd = fd;
  handle->base_addr = base;
  return Error::Success;
}

Error GetXlaSharedMemoryRawHandle(
    const XlaShmHandle& handle, std::vector<uint8_t>* raw_handle) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  char buf[256];
  int n = snprintf(
      buf, sizeof(buf),
      "{\"uuid\": \"%s\", \"staging_key\": \"%s\", \"byte_size\": %zu, "
      "\"device_id\": %d}",
      handle.uuid.c_str(), handle.staging_key.c_str(), handle.byte_size,
      handle.device_id);
  raw_handle->assign(buf, buf + n);
  return Error::Success;
}

Error SetXlaSharedMemoryRegion(
    const XlaShmHandle& handle, const void* data, size_t byte_size,
    size_t offset) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  // overflow-safe bounds check: offset + byte_size could wrap size_t
  if (offset > handle.byte_size || byte_size > handle.byte_size - offset) {
    return Error(
        "write of " + std::to_string(byte_size) + " bytes at offset " +
        std::to_string(offset) + " exceeds region size " +
        std::to_string(handle.byte_size));
  }
  memcpy(static_cast<uint8_t*>(handle.base_addr) + offset, data, byte_size);
  return Error::Success;
}

Error GetXlaSharedMemoryContents(
    const XlaShmHandle& handle, void* out, size_t byte_size, size_t offset) {
  if (handle.base_addr == nullptr) {
    return Error("region '" + handle.triton_shm_name + "' is not allocated");
  }
  if (offset > handle.byte_size || byte_size > handle.byte_size - offset) {
    return Error(
        "read of " + std::to_string(byte_size) + " bytes at offset " +
        std::to_string(offset) + " exceeds region size " +
        std::to_string(handle.byte_size));
  }
  memcpy(out, static_cast<const uint8_t*>(handle.base_addr) + offset,
         byte_size);
  return Error::Success;
}

Error DestroyXlaSharedMemoryRegion(XlaShmHandle* handle) {
  if (handle->base_addr != nullptr) {
    ::munmap(handle->base_addr, handle->byte_size);
    handle->base_addr = nullptr;
  }
  if (handle->shm_fd >= 0) {
    ::close(handle->shm_fd);
    handle->shm_fd = -1;
  }
  if (!handle->staging_key.empty()) {
    ::shm_unlink(handle->staging_key.c_str());
    handle->staging_key.clear();
  }
  return Error::Success;
}

}  // namespace client
}  // namespace tc_tpu
