// C++ gRPC client.
//
// Parity target: reference src/c++/library/grpc_client.h (642 LoC) — same
// public API: Create, health/metadata/config/repository/statistics/
// trace/log/shm methods returning protobuf messages, Infer, AsyncInfer,
// and streaming inference.
//
// Transport re-design: the image ships no grpc++ headers, so the protocol
// is implemented directly.  Default wire: **real gRPC over HTTP/2**
// (own RFC 7540 framing + HPACK, h2.{h,cc}) against the stock gRPC port —
// h2c prior knowledge in the clear, TLS + ALPN "h2" with use_ssl (real
// grpcs) — wire-compatible with any v2 gRPC endpoint.
// The first RPC probes the endpoint; an HTTP/1.1 server (this repo's
// grpc-web bridge) answers the h2c preface with HTTP text and the client
// transparently falls back to standard **gRPC-Web** framing
// (``application/grpc-web+proto``) over the shared HTTP/1.1 socket
// transport.  TC_TPU_GRPC_TRANSPORT=h2|web pins the mode.  The pb messages
// are generated from the same inference.proto the Python stack uses, so
// wire semantics match the reference's gRPC client in both modes.
// Streaming is live and bidirectional in both modes: a real HTTP/2 bidi
// stream (h2c) or chunked-transfer duplex frames (web), with responses
// delivered from a dedicated reader thread while the stream is open.
#pragma once

#include <condition_variable>
#include <deque>
#include <functional>
#include <map>
#include <memory>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

#include "common.h"
#include "h2.h"
#include "inference.pb.h"
#include "transport.h"

namespace tc_tpu {
namespace client {

namespace pb = ::inference;

class InferResultGrpc;

// gRPC keepalive knobs (reference grpc_client.h:62-86). On this gRPC-Web
// socket transport, HTTP/2 keepalive pings translate to TCP keepalive
// probes: keepalive_time_ms → TCP_KEEPIDLE, keepalive_timeout_ms →
// TCP_KEEPINTVL. keepalive_permit_without_calls keeps pooled idle
// connections probed too (always true for a TCP-level probe);
// http2_max_pings_without_data has no HTTP/1.1 equivalent and is accepted
// for API compatibility.
struct KeepAliveOptions {
  int keepalive_time_ms = 0x7fffffff;
  int keepalive_timeout_ms = 20000;
  bool keepalive_permit_without_calls = false;
  int http2_max_pings_without_data = 2;
};

// Generic channel-argument list (reference grpc::ChannelArguments used by
// simple_grpc_custom_args_client.cc:105-116). Recognized keys map onto the
// socket transport; unrecognized keys are kept (visible via args()) and
// ignored, matching gRPC's pass-through semantics for unknown args.
class ChannelArguments {
 public:
  void SetInt(const std::string& key, int value) {
    args_[key] = std::to_string(value);
  }
  void SetString(const std::string& key, const std::string& value) {
    args_[key] = value;
  }
  // named for parity with grpc::ChannelArguments
  void SetMaxSendMessageSize(int bytes) {
    SetInt("grpc.max_send_message_length", bytes);
  }
  void SetMaxReceiveMessageSize(int bytes) {
    SetInt("grpc.max_receive_message_length", bytes);
  }
  const std::map<std::string, std::string>& args() const { return args_; }

 private:
  std::map<std::string, std::string> args_;
};

class InferenceServerGrpcClient : public InferenceServerClient {
 public:
  using OnCompleteFn = std::function<void(InferResult*)>;

  // `use_cached_channel` shares one transport (socket + h2 connection
  // pool) among up to TRITON_CLIENT_GRPC_CHANNEL_MAX_SHARE_COUNT clients
  // of the same url (reference channel cache, grpc_client.cc:47-152,
  // default 6); false forces a private transport.  Clients created with
  // keepalive/channel-args/ssl customization always get private
  // transports (their options mutate transport state).
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, bool verbose = false,
      bool use_cached_channel = true);
  // keepalive-configured channel (reference grpc_client.cc Create overload
  // with KeepAliveOptions)
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, bool verbose,
      const KeepAliveOptions& keepalive_options);
  // custom channel arguments (reference Create overload taking
  // grpc::ChannelArguments)
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, const ChannelArguments& channel_args,
      bool verbose = false);
  // Secure channel (reference Create overload taking use_ssl + SslOptions,
  // grpc_client.h).  Divergence: the reference's SslOptions carry PEM
  // *contents*; these carry file *paths* (the TLS layer loads them).  The
  // secure wire is REAL gRPC over TLS (ALPN "h2") against the stock
  // secure gRPC port; an HTTPS endpoint that negotiates http/1.1 (the web
  // bridge) transparently falls back to gRPC-Web over TLS.
  struct GrpcSslOptions {
    std::string root_certificates;   // CA bundle path ("" = system default)
    std::string private_key;         // client key path (mTLS)
    std::string certificate_chain;   // client cert path (mTLS)
  };
  // (no default on ssl_options: a 4-arg bool,bool call must bind to the
  // use_cached_channel overload above, not silently enable TLS)
  static Error Create(
      std::unique_ptr<InferenceServerGrpcClient>* client,
      const std::string& server_url, bool verbose, bool use_ssl,
      const GrpcSslOptions& ssl_options);
  ~InferenceServerGrpcClient() override;

  Error IsServerLive(bool* live, const Headers& headers = Headers());
  Error IsServerReady(bool* ready, const Headers& headers = Headers());
  Error IsModelReady(
      bool* ready, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());

  Error ServerMetadata(
      pb::ServerMetadataResponse* server_metadata,
      const Headers& headers = Headers());
  Error ModelMetadata(
      pb::ModelMetadataResponse* model_metadata, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());
  Error ModelConfig(
      pb::ModelConfigResponse* model_config, const std::string& model_name,
      const std::string& model_version = "",
      const Headers& headers = Headers());

  Error ModelRepositoryIndex(
      pb::RepositoryIndexResponse* repository_index,
      const Headers& headers = Headers());
  Error LoadModel(
      const std::string& model_name, const Headers& headers = Headers(),
      const std::string& config = "",
      const std::map<std::string, std::vector<char>>& files = {});
  Error UnloadModel(
      const std::string& model_name, const Headers& headers = Headers());

  Error ModelInferenceStatistics(
      pb::ModelStatisticsResponse* infer_stat,
      const std::string& model_name = "",
      const std::string& model_version = "",
      const Headers& headers = Headers());

  // Server trace/log management (reference grpc client trace RPCs,
  // grpc/_client.py:832-979 — the client configures server tracing).
  Error UpdateTraceSettings(
      pb::TraceSettingResponse* response, const std::string& model_name = "",
      const std::map<std::string, std::vector<std::string>>& settings = {},
      const Headers& headers = Headers());
  Error GetTraceSettings(
      pb::TraceSettingResponse* settings, const std::string& model_name = "",
      const Headers& headers = Headers());
  Error UpdateLogSettings(
      pb::LogSettingsResponse* response,
      const std::map<std::string, std::string>& settings = {},
      const Headers& headers = Headers());
  Error GetLogSettings(
      pb::LogSettingsResponse* settings,
      const Headers& headers = Headers());

  Error SystemSharedMemoryStatus(
      pb::SystemSharedMemoryStatusResponse* status,
      const std::string& region_name = "", const Headers& headers = Headers());
  Error RegisterSystemSharedMemory(
      const std::string& name, const std::string& key, size_t byte_size,
      size_t offset = 0, const Headers& headers = Headers());
  Error UnregisterSystemSharedMemory(
      const std::string& name = "", const Headers& headers = Headers());
  Error CudaSharedMemoryStatus(
      pb::CudaSharedMemoryStatusResponse* status,
      const std::string& region_name = "", const Headers& headers = Headers());
  Error RegisterCudaSharedMemory(
      const std::string& name, const std::vector<uint8_t>& raw_handle,
      size_t device_id, size_t byte_size, const Headers& headers = Headers());
  Error UnregisterCudaSharedMemory(
      const std::string& name = "", const Headers& headers = Headers());

  Error Infer(
      InferResult** result, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = Headers());

  Error AsyncInfer(
      OnCompleteFn callback, const InferOptions& options,
      const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {},
      const Headers& headers = Headers());

  // Fan-out over multiple requests (reference InferMulti/AsyncInferMulti;
  // options/outputs broadcast when single-element, else one per request).
  using OnMultiCompleteFn = std::function<void(std::vector<InferResult*>)>;
  Error InferMulti(
      std::vector<InferResult*>* results,
      const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = Headers());
  Error AsyncInferMulti(
      OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
      const std::vector<std::vector<InferInput*>>& inputs,
      const std::vector<std::vector<const InferRequestedOutput*>>& outputs =
          {},
      const Headers& headers = Headers());

  // Live bidirectional streaming (reference grpc_client.cc:1377-1673
  // ClientReaderWriter + AsyncStreamTransfer reader thread): StartStream
  // opens a duplex gRPC-Web exchange and spawns a reader thread; every
  // AsyncStreamInfer sends its request immediately as an HTTP chunk; each
  // response is delivered through the callback AS IT ARRIVES, while the
  // stream stays open — interleaved sequences and decoupled models work in
  // real time.  FinishStream closes the request side, drains remaining
  // responses, and returns the stream's final status.
  Error StartStream(OnCompleteFn callback, const Headers& headers = Headers());
  Error AsyncStreamInfer(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs = {});
  Error FinishStream();

 private:
  InferenceServerGrpcClient(const std::string& url, bool verbose);

  Error Call(
      const std::string& method, const google::protobuf::Message& request,
      google::protobuf::Message* response, const Headers& headers,
      RequestTimers* timers = nullptr, uint64_t timeout_us = 0);
  Error CallWeb(
      const std::string& method, const google::protobuf::Message& request,
      google::protobuf::Message* response, const Headers& headers,
      RequestTimers* timers, uint64_t timeout_us);
  Error CallH2(
      const std::string& method, const google::protobuf::Message& request,
      google::protobuf::Message* response, const Headers& headers,
      RequestTimers* timers, uint64_t timeout_us);
  static Error BuildInferRequest(
      const InferOptions& options, const std::vector<InferInput*>& inputs,
      const std::vector<const InferRequestedOutput*>& outputs,
      pb::ModelInferRequest* request);

  std::shared_ptr<HttpTransport> transport_;
  std::string cached_url_;  // non-empty: release the cache ref at dtor

 public:
  // introspection for tests: how many owners share this client's transport
  // (cache entry + clients); 1 means a private transport
  long TransportUseCount() const { return transport_.use_count(); }

 private:

  // ---- transport mode: real gRPC (h2c) vs the gRPC-Web bridge ----
  // kUndecided probes on the first RPC: an h2c prior-knowledge handshake
  // against the endpoint — a stock gRPC port accepts it; an HTTP/1.1
  // bridge answers with HTTP text and the client falls back to web
  // framing.  TC_TPU_GRPC_TRANSPORT=h2|web pins the mode explicitly.
  enum class Mode { kUndecided, kH2, kWeb };
  Error EnsureMode(uint64_t timeout_us);
  Error AcquireH2(std::unique_ptr<H2GrpcConnection>* conn,
                  uint64_t timeout_us);
  void ReleaseH2(std::unique_ptr<H2GrpcConnection> conn, bool reusable);
  // The multiplexed unary channel: concurrent unary RPCs share ONE socket
  // (grpc++ parity); replaced transparently when it dies.  Returns a
  // shared handle so a replacement never pulls the connection out from
  // under an in-flight call.
  Error AcquireMux(std::shared_ptr<H2GrpcConnection>* conn,
                   uint64_t timeout_us);

  std::mutex mode_mu_;
  Mode mode_ = Mode::kUndecided;
  std::vector<std::unique_ptr<H2GrpcConnection>> h2_idle_;
  std::shared_ptr<H2GrpcConnection> h2_mux_;

  // async worker
  void AsyncTransfer();
  struct AsyncJob {
    OnCompleteFn callback;
    pb::ModelInferRequest request;
    Headers headers;
    uint64_t timeout_us = 0;
  };
  std::mutex job_mu_;
  std::condition_variable job_cv_;
  std::deque<AsyncJob> jobs_;
  std::vector<std::thread> workers_;
  bool exiting_ = false;

  // streaming state
  void StreamReadLoop();
  void StreamReadLoopH2();
  OnCompleteFn stream_callback_;
  std::unique_ptr<DuplexConnection> stream_conn_;
  std::unique_ptr<H2GrpcConnection> h2_stream_conn_;
  std::thread stream_reader_;
  std::mutex stream_write_mu_;
  std::mutex stream_err_mu_;
  Error stream_final_error_;  // trailers status / transport error
  bool stream_active_ = false;
};

}  // namespace client
}  // namespace tc_tpu
