#include "http_client.h"

#include <zlib.h>

#include <algorithm>
#include <cstdio>
#include <cstring>
#include <sstream>

#include "infer_multi.h"

namespace tc_tpu {
namespace client {

namespace {

// zlib body compression (reference CompressInput, http_client.cc:720):
// DEFLATE = raw zlib stream, GZIP = zlib with gzip wrapper.
Error ZCompress(
    const std::string& in,
    InferenceServerHttpClient::CompressionType type, std::string* out) {
  int window_bits =
      type == InferenceServerHttpClient::CompressionType::GZIP ? 15 + 16 : 15;
  z_stream zs = {};
  if (deflateInit2(&zs, Z_DEFAULT_COMPRESSION, Z_DEFLATED, window_bits, 8,
                   Z_DEFAULT_STRATEGY) != Z_OK) {
    return Error("failed to initialize compression stream");
  }
  out->resize(deflateBound(&zs, in.size()));
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  zs.next_out = reinterpret_cast<Bytef*>(&(*out)[0]);
  zs.avail_out = static_cast<uInt>(out->size());
  int rc = deflate(&zs, Z_FINISH);
  deflateEnd(&zs);
  if (rc != Z_STREAM_END) {
    return Error("failed to compress request body");
  }
  out->resize(zs.total_out);
  return Error::Success;
}

Error ZDecompress(const std::string& in, const std::string& encoding,
                  std::string* out) {
  // 15+32: auto-detect zlib or gzip wrapper
  z_stream zs = {};
  if (inflateInit2(&zs, 15 + 32) != Z_OK) {
    return Error("failed to initialize decompression stream");
  }
  zs.next_in = reinterpret_cast<Bytef*>(const_cast<char*>(in.data()));
  zs.avail_in = static_cast<uInt>(in.size());
  out->clear();
  char buf[16384];
  int rc = Z_OK;
  do {
    zs.next_out = reinterpret_cast<Bytef*>(buf);
    zs.avail_out = sizeof(buf);
    rc = inflate(&zs, Z_NO_FLUSH);
    if (rc != Z_OK && rc != Z_STREAM_END) {
      inflateEnd(&zs);
      return Error("failed to decompress '" + encoding + "' response body");
    }
    out->append(buf, sizeof(buf) - zs.avail_out);
  } while (rc != Z_STREAM_END);
  inflateEnd(&zs);
  return Error::Success;
}

const char* EncodingName(InferenceServerHttpClient::CompressionType t) {
  switch (t) {
    case InferenceServerHttpClient::CompressionType::DEFLATE:
      return "deflate";
    case InferenceServerHttpClient::CompressionType::GZIP:
      return "gzip";
    default:
      return "";
  }
}

}  // namespace

Error InferenceServerHttpClient::Create(
    std::unique_ptr<InferenceServerHttpClient>* client,
    const std::string& server_url, bool verbose, size_t concurrency,
    bool use_ssl, const HttpSslOptions& ssl_options) {
  if (server_url.rfind("http://", 0) == 0 ||
      server_url.rfind("https://", 0) == 0) {
    return Error("url should not include the scheme");
  }
  client->reset(
      new InferenceServerHttpClient(server_url, verbose, concurrency));
  if ((*client)->transport_->port() <= 0) {
    return Error("invalid server url '" + server_url + "'");
  }
  if (use_ssl) {
    // HTTPS via the system libssl (reference HttpSslOptions / libcurl
    // CURLOPT_SSL_*, http_client.h:45-86)
    HttpSslOptionsView view;
    view.verify_peer = ssl_options.verify_peer;
    view.verify_host = ssl_options.verify_host;
    view.ca_info = ssl_options.ca_info;
    view.cert = ssl_options.cert;
    view.cert_pem =
        ssl_options.cert_type == HttpSslOptions::CERTTYPE::CERT_PEM;
    view.key = ssl_options.key;
    view.key_pem = ssl_options.key_type == HttpSslOptions::KEYTYPE::KEY_PEM;
    TC_RETURN_IF_ERROR((*client)->transport_->EnableTls(view));
  }
  return Error::Success;
}

InferenceServerHttpClient::InferenceServerHttpClient(
    const std::string& url, bool verbose, size_t concurrency)
    : InferenceServerClient(verbose), concurrency_(concurrency) {
  std::string host = url;
  int port = 8000;
  auto colon = url.rfind(':');
  if (colon != std::string::npos) {
    host = url.substr(0, colon);
    port = atoi(url.substr(colon + 1).c_str());
  }
  transport_.reset(new HttpTransport(host, port, concurrency));
}

InferenceServerHttpClient::~InferenceServerHttpClient() {
  {
    std::lock_guard<std::mutex> lk(job_mu_);
    exiting_ = true;
  }
  job_cv_.notify_all();
  for (auto& w : workers_) {
    if (w.joinable()) w.join();
  }
}

Error InferenceServerHttpClient::Get(
    const std::string& path, const Headers& headers, Response* out) {
  Error err = transport_->Request("GET", path, "", headers, out);
  if (err.IsOk() && verbose_) {
    fprintf(stderr, "GET /%s -> %d (%zu bytes)\n", path.c_str(), out->status,
            out->body.size());
  }
  return err;
}

Error InferenceServerHttpClient::Post(
    const std::string& path, const std::string& body, const Headers& headers,
    Response* out, RequestTimers* timers, uint64_t timeout_us) {
  Error err = transport_->Request(
      "POST", path, body, headers, out, timers, timeout_us);
  if (err.IsOk() && verbose_) {
    fprintf(stderr, "POST /%s -> %d (%zu bytes)\n", path.c_str(), out->status,
            out->body.size());
  }
  return err;
}

Error InferenceServerHttpClient::CheckResponse(const Response& resp) {
  if (resp.status >= 200 && resp.status < 300) return Error::Success;
  json::Value doc;
  std::string jerr;
  if (json::Parse(resp.body, &doc, &jerr) && doc.Has("error")) {
    return Error(doc.At("error").AsString());
  }
  return Error(
      "request failed with status " + std::to_string(resp.status) +
      (resp.body.empty() ? "" : (": " + resp.body)));
}

//==============================================================================
// health / metadata / repository / statistics / settings

Error InferenceServerHttpClient::IsServerLive(bool* live, const Headers& headers) {
  Response resp;
  TC_RETURN_IF_ERROR(Get("v2/health/live", headers, &resp));
  *live = (resp.status == 200);
  return Error::Success;
}

Error InferenceServerHttpClient::IsServerReady(bool* ready, const Headers& headers) {
  Response resp;
  TC_RETURN_IF_ERROR(Get("v2/health/ready", headers, &resp));
  *ready = (resp.status == 200);
  return Error::Success;
}

Error InferenceServerHttpClient::IsModelReady(
    bool* ready, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  path += "/ready";
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  *ready = (resp.status == 200);
  return Error::Success;
}

Error InferenceServerHttpClient::ServerMetadata(
    std::string* server_metadata, const Headers& headers) {
  Response resp;
  TC_RETURN_IF_ERROR(Get("v2", headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *server_metadata = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::ModelMetadata(
    std::string* model_metadata, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *model_metadata = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::ModelConfig(
    std::string* model_config, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path = "v2/models/" + model_name;
  if (!model_version.empty()) path += "/versions/" + model_version;
  path += "/config";
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *model_config = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::ModelRepositoryIndex(
    std::string* repository_index, const Headers& headers) {
  Response resp;
  TC_RETURN_IF_ERROR(Post("v2/repository/index", "", headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *repository_index = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::LoadModel(
    const std::string& model_name, const Headers& headers,
    const std::string& config,
    const std::map<std::string, std::vector<char>>& files) {
  json::Object params;
  if (!config.empty()) params.emplace("config", json::Value(config));
  for (const auto& kv : files) {
    params.emplace(
        kv.first, json::Value(Base64Encode(
                      reinterpret_cast<const uint8_t*>(kv.second.data()),
                      kv.second.size())));
  }
  std::string body;
  if (!params.empty()) {
    json::Object root;
    root.emplace("parameters", json::Value(std::move(params)));
    body = json::Value(std::move(root)).Serialize();
  }
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(
      Post("v2/repository/models/" + model_name + "/load", body, h, &resp));
  return CheckResponse(resp);
}

Error InferenceServerHttpClient::UnloadModel(
    const std::string& model_name, const Headers& headers) {
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(
      Post("v2/repository/models/" + model_name + "/unload", "{}", h, &resp));
  return CheckResponse(resp);
}

Error InferenceServerHttpClient::ModelInferenceStatistics(
    std::string* infer_stat, const std::string& model_name,
    const std::string& model_version, const Headers& headers) {
  std::string path;
  if (!model_name.empty()) {
    path = "v2/models/" + model_name;
    if (!model_version.empty()) path += "/versions/" + model_version;
    path += "/stats";
  } else {
    path = "v2/models/stats";
  }
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *infer_stat = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::UpdateTraceSettings(
    std::string* response, const std::string& model_name,
    const std::map<std::string, std::vector<std::string>>& settings,
    const Headers& headers) {
  json::Object obj;
  for (const auto& kv : settings) {
    json::Array arr;
    for (const auto& v : kv.second) arr.emplace_back(v);
    obj.emplace(kv.first, json::Value(std::move(arr)));
  }
  std::string path = model_name.empty()
                         ? "v2/trace/setting"
                         : ("v2/models/" + model_name + "/trace/setting");
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(
      Post(path, json::Value(std::move(obj)).Serialize(), h, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  if (response) *response = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::GetTraceSettings(
    std::string* settings, const std::string& model_name,
    const Headers& headers) {
  std::string path = model_name.empty()
                         ? "v2/trace/setting"
                         : ("v2/models/" + model_name + "/trace/setting");
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *settings = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::UpdateLogSettings(
    std::string* response, const std::map<std::string, std::string>& settings,
    const Headers& headers) {
  json::Object obj;
  for (const auto& kv : settings) obj.emplace(kv.first, json::Value(kv.second));
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(
      Post("v2/logging", json::Value(std::move(obj)).Serialize(), h, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  if (response) *response = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::GetLogSettings(
    std::string* settings, const Headers& headers) {
  Response resp;
  TC_RETURN_IF_ERROR(Get("v2/logging", headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *settings = resp.body;
  return Error::Success;
}

//==============================================================================
// shared memory management

Error InferenceServerHttpClient::SystemSharedMemoryStatus(
    std::string* status, const std::string& region_name,
    const Headers& headers) {
  std::string path = "v2/systemsharedmemory";
  if (!region_name.empty()) path += "/region/" + region_name;
  path += "/status";
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *status = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::RegisterSystemSharedMemory(
    const std::string& name, const std::string& key, size_t byte_size,
    size_t offset, const Headers& headers) {
  json::Object obj;
  obj.emplace("key", json::Value(key));
  obj.emplace("offset", json::Value(offset));
  obj.emplace("byte_size", json::Value(byte_size));
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(Post(
      "v2/systemsharedmemory/region/" + name + "/register",
      json::Value(std::move(obj)).Serialize(), h, &resp));
  return CheckResponse(resp);
}

Error InferenceServerHttpClient::UnregisterSystemSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path = name.empty()
                         ? "v2/systemsharedmemory/unregister"
                         : ("v2/systemsharedmemory/region/" + name + "/unregister");
  Response resp;
  TC_RETURN_IF_ERROR(Post(path, "", headers, &resp));
  return CheckResponse(resp);
}

Error InferenceServerHttpClient::CudaSharedMemoryStatus(
    std::string* status, const std::string& region_name,
    const Headers& headers) {
  std::string path = "v2/cudasharedmemory";
  if (!region_name.empty()) path += "/region/" + region_name;
  path += "/status";
  Response resp;
  TC_RETURN_IF_ERROR(Get(path, headers, &resp));
  TC_RETURN_IF_ERROR(CheckResponse(resp));
  *status = resp.body;
  return Error::Success;
}

Error InferenceServerHttpClient::RegisterCudaSharedMemory(
    const std::string& name, const std::vector<uint8_t>& raw_handle,
    size_t device_id, size_t byte_size, const Headers& headers) {
  json::Object handle;
  handle.emplace("b64", json::Value(Base64Encode(raw_handle.data(),
                                                 raw_handle.size())));
  json::Object obj;
  obj.emplace("raw_handle", json::Value(std::move(handle)));
  obj.emplace("device_id", json::Value(device_id));
  obj.emplace("byte_size", json::Value(byte_size));
  Response resp;
  Headers h = headers;
  h["Content-Type"] = "application/json";
  TC_RETURN_IF_ERROR(Post(
      "v2/cudasharedmemory/region/" + name + "/register",
      json::Value(std::move(obj)).Serialize(), h, &resp));
  return CheckResponse(resp);
}

Error InferenceServerHttpClient::UnregisterCudaSharedMemory(
    const std::string& name, const Headers& headers) {
  std::string path = name.empty()
                         ? "v2/cudasharedmemory/unregister"
                         : ("v2/cudasharedmemory/region/" + name + "/unregister");
  Response resp;
  TC_RETURN_IF_ERROR(Post(path, "", headers, &resp));
  return CheckResponse(resp);
}

//==============================================================================
// inference

namespace {

// Result over the binary-over-HTTP response framing (reference
// InferResultHttp, http_client.cc:740-1283).
class InferResultHttpImpl : public InferResult {
 public:
  static Error Create(
      InferResult** result, std::string body, size_t header_length) {
    auto* r = new InferResultHttpImpl(std::move(body));
    Error err = r->Parse(header_length);
    if (!err.IsOk()) {
      delete r;
      return err;
    }
    *result = r;
    return Error::Success;
  }

  Error ModelName(std::string* name) const override {
    *name = doc_.At("model_name").AsString();
    return Error::Success;
  }
  Error ModelVersion(std::string* version) const override {
    *version = doc_.At("model_version").AsString();
    return Error::Success;
  }
  Error Id(std::string* id) const override {
    *id = doc_.At("id").AsString();
    return Error::Success;
  }

  Error Shape(const std::string& output_name,
              std::vector<int64_t>* shape) const override {
    const json::Value* out = FindOutput(output_name);
    if (!out) return Error("output '" + output_name + "' not found");
    shape->clear();
    for (const auto& d : out->At("shape").AsArray()) shape->push_back(d.AsInt());
    return Error::Success;
  }

  Error Datatype(const std::string& output_name,
                 std::string* datatype) const override {
    const json::Value* out = FindOutput(output_name);
    if (!out) return Error("output '" + output_name + "' not found");
    *datatype = out->At("datatype").AsString();
    return Error::Success;
  }

  Error RawData(const std::string& output_name, const uint8_t** buf,
                size_t* byte_size) const override {
    auto it = segments_.find(output_name);
    if (it == segments_.end()) {
      return Error("output '" + output_name + "' has no binary data");
    }
    *buf = reinterpret_cast<const uint8_t*>(body_.data()) + it->second.first;
    *byte_size = it->second.second;
    return Error::Success;
  }

  Error RequestStatus() const override { return Error::Success; }
  std::string DebugString() const override { return doc_.Serialize(); }

 private:
  explicit InferResultHttpImpl(std::string body) : body_(std::move(body)) {}

  Error Parse(size_t header_length) {
    size_t jlen = header_length ? header_length : body_.size();
    std::string err;
    if (!json::Parse(body_.data(), jlen, &doc_, &err)) {
      return Error("failed to parse inference response JSON: " + err);
    }
    size_t offset = jlen;
    for (const auto& out : doc_.At("outputs").AsArray()) {
      const auto& params = out.At("parameters");
      if (params.Has("binary_data_size")) {
        size_t sz = static_cast<size_t>(params.At("binary_data_size").AsInt());
        if (offset + sz > body_.size()) {
          return Error("binary segment exceeds response body");
        }
        segments_[out.At("name").AsString()] = {offset, sz};
        offset += sz;
      }
    }
    return Error::Success;
  }

  const json::Value* FindOutput(const std::string& name) const {
    for (const auto& out : doc_.At("outputs").AsArray()) {
      if (out.At("name").AsString() == name) return &out;
    }
    return nullptr;
  }

  std::string body_;
  json::Value doc_;
  std::map<std::string, std::pair<size_t, size_t>> segments_;
};

}  // namespace

Error InferenceServerHttpClient::BuildInferRequestBody(
    const InferOptions& options, const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    std::string* body, size_t* header_length) {
  json::Object root;
  if (!options.request_id_.empty()) {
    root.emplace("id", json::Value(options.request_id_));
  }
  json::Object params;
  if (!options.sequence_id_str_.empty()) {
    params.emplace("sequence_id", json::Value(options.sequence_id_str_));
  } else if (options.sequence_id_ != 0) {
    params.emplace("sequence_id", json::Value(options.sequence_id_));
  }
  if (options.sequence_id_ != 0 || !options.sequence_id_str_.empty()) {
    params.emplace("sequence_start", json::Value(options.sequence_start_));
    params.emplace("sequence_end", json::Value(options.sequence_end_));
  }
  if (options.priority_ != 0) {
    params.emplace("priority", json::Value(options.priority_));
  }
  if (options.server_timeout_us_ != 0) {
    params.emplace("timeout", json::Value(options.server_timeout_us_));
  }
  for (const auto& kv : options.request_parameters_) {
    params.emplace(kv.first, json::Value(kv.second));
  }
  if (!params.empty()) {
    root.emplace("parameters", json::Value(std::move(params)));
  }

  size_t total_binary = 0;
  json::Array jinputs;
  for (InferInput* input : inputs) {
    json::Object jin;
    jin.emplace("name", json::Value(input->Name()));
    jin.emplace("datatype", json::Value(input->Datatype()));
    json::Array shape;
    for (int64_t d : input->Shape()) shape.emplace_back(d);
    jin.emplace("shape", json::Value(std::move(shape)));
    json::Object iparams;
    if (input->Type() == InferInput::IOType::kSharedMemory) {
      iparams.emplace("shared_memory_region",
                      json::Value(input->SharedMemoryRegion()));
      iparams.emplace("shared_memory_byte_size",
                      json::Value(input->SharedMemoryByteSize()));
      if (input->SharedMemoryOffset() != 0) {
        iparams.emplace("shared_memory_offset",
                        json::Value(input->SharedMemoryOffset()));
      }
    } else {
      iparams.emplace("binary_data_size", json::Value(input->TotalByteSize()));
      total_binary += input->TotalByteSize();
    }
    jin.emplace("parameters", json::Value(std::move(iparams)));
    jinputs.push_back(json::Value(std::move(jin)));
  }
  root.emplace("inputs", json::Value(std::move(jinputs)));

  if (!outputs.empty()) {
    json::Array jouts;
    for (const InferRequestedOutput* output : outputs) {
      json::Object jout;
      jout.emplace("name", json::Value(output->Name()));
      json::Object oparams;
      oparams.emplace("binary_data", json::Value(!output->IsSharedMemory()));
      if (output->ClassCount() > 0) {
        oparams.emplace("classification", json::Value(output->ClassCount()));
      }
      if (output->IsSharedMemory()) {
        oparams.emplace("shared_memory_region",
                        json::Value(output->SharedMemoryRegion()));
        oparams.emplace("shared_memory_byte_size",
                        json::Value(output->SharedMemoryByteSize()));
        if (output->SharedMemoryOffset() != 0) {
          oparams.emplace("shared_memory_offset",
                          json::Value(output->SharedMemoryOffset()));
        }
      }
      jout.emplace("parameters", json::Value(std::move(oparams)));
      jouts.push_back(json::Value(std::move(jout)));
    }
    root.emplace("outputs", json::Value(std::move(jouts)));
  }

  std::string json_part = json::Value(std::move(root)).Serialize();
  *header_length = json_part.size();
  body->clear();
  body->reserve(json_part.size() + total_binary);
  *body = std::move(json_part);
  for (InferInput* input : inputs) {
    if (input->Type() == InferInput::IOType::kSharedMemory) continue;
    input->PrepareForRequest();
    bool end = false;
    while (!end) {
      const uint8_t* ptr = nullptr;
      size_t len = 0;
      TC_RETURN_IF_ERROR(input->GetNext(&ptr, &len, &end));
      if (ptr && len) body->append(reinterpret_cast<const char*>(ptr), len);
    }
  }
  return Error::Success;
}

Error InferenceServerHttpClient::DoInfer(
    InferResult** result, const std::string& path, std::string body,
    size_t header_length, const Headers& headers, uint64_t timeout_us,
    CompressionType request_compression, CompressionType response_compression,
    RequestTimers* timers) {
  Headers h = headers;
  h["Inference-Header-Content-Length"] = std::to_string(header_length);
  h["Content-Type"] = "application/octet-stream";
  if (request_compression != CompressionType::NONE) {
    std::string compressed;
    TC_RETURN_IF_ERROR(ZCompress(body, request_compression, &compressed));
    body = std::move(compressed);
    h["Content-Encoding"] = EncodingName(request_compression);
  }
  if (response_compression != CompressionType::NONE) {
    h["Accept-Encoding"] = EncodingName(response_compression);
  }

  Response resp;
  TC_RETURN_IF_ERROR(Post(path, body, h, &resp, timers, timeout_us));
  auto enc = resp.headers.find("content-encoding");
  if (enc != resp.headers.end() && !enc->second.empty() &&
      enc->second != "identity") {
    std::string plain;
    TC_RETURN_IF_ERROR(ZDecompress(resp.body, enc->second, &plain));
    resp.body = std::move(plain);
  }
  TC_RETURN_IF_ERROR(CheckResponse(resp));

  size_t resp_header_len = 0;
  auto it = resp.headers.find("inference-header-content-length");
  if (it != resp.headers.end()) {
    resp_header_len = strtoul(it->second.c_str(), nullptr, 10);
  }
  return InferResultHttpImpl::Create(
      result, std::move(resp.body), resp_header_len);
}

namespace {

std::string InferPath(const InferOptions& options) {
  std::string path = "v2/models/" + options.model_name_;
  if (!options.model_version_.empty()) {
    path += "/versions/" + options.model_version_;
  }
  return path + "/infer";
}

}  // namespace

Error InferenceServerHttpClient::Infer(
    InferResult** result, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, CompressionType request_compression_algorithm,
    CompressionType response_compression_algorithm) {
  RequestTimers timers;
  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);

  std::string body;
  size_t header_length = 0;
  TC_RETURN_IF_ERROR(
      BuildInferRequestBody(options, inputs, outputs, &body, &header_length));
  TC_RETURN_IF_ERROR(DoInfer(
      result, InferPath(options), std::move(body), header_length, headers,
      options.client_timeout_us_, request_compression_algorithm,
      response_compression_algorithm, &timers));

  timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
  UpdateInferStat(timers);
  return Error::Success;
}

Error InferenceServerHttpClient::AsyncInfer(
    OnCompleteFn callback, const InferOptions& options,
    const std::vector<InferInput*>& inputs,
    const std::vector<const InferRequestedOutput*>& outputs,
    const Headers& headers, CompressionType request_compression_algorithm,
    CompressionType response_compression_algorithm) {
  if (callback == nullptr) {
    return Error("callback must not be null for AsyncInfer");
  }
  std::string body;
  size_t header_length = 0;
  TC_RETURN_IF_ERROR(
      BuildInferRequestBody(options, inputs, outputs, &body, &header_length));

  {
    std::lock_guard<std::mutex> lk(job_mu_);
    if (workers_.empty()) {
      for (size_t i = 0; i < std::max<size_t>(concurrency_, 1); ++i) {
        workers_.emplace_back(&InferenceServerHttpClient::AsyncTransfer, this);
      }
    }
    jobs_.push_back(AsyncJob{
        std::move(callback), InferPath(options), std::move(body), headers,
        header_length, options.client_timeout_us_,
        request_compression_algorithm, response_compression_algorithm});
  }
  job_cv_.notify_one();
  return Error::Success;
}

void InferenceServerHttpClient::AsyncTransfer() {
  while (true) {
    AsyncJob job;
    {
      std::unique_lock<std::mutex> lk(job_mu_);
      job_cv_.wait(lk, [this] { return exiting_ || !jobs_.empty(); });
      if (exiting_ && jobs_.empty()) return;
      job = std::move(jobs_.front());
      jobs_.pop_front();
    }
    RequestTimers timers;
    timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_START);
    InferResult* result = nullptr;
    Error err = DoInfer(
        &result, job.path, std::move(job.body), job.header_length,
        job.headers, job.timeout_us, job.request_compression,
        job.response_compression, &timers);
    if (!err.IsOk()) {
      result = new ErrorResult(err);
    } else {
      timers.CaptureTimestamp(RequestTimers::Kind::REQUEST_END);
      {
        std::lock_guard<std::mutex> lk(job_mu_);
        UpdateInferStat(timers);
      }
    }
    job.callback(result);
  }
}

//==============================================================================
Error InferenceServerHttpClient::InferMulti(
    std::vector<InferResult*>* results, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  return multi_detail::InferMultiImpl(
      results, options, inputs, outputs,
      [&](InferResult** result, const InferOptions& opt, const auto& ins,
          const auto& outs) {
        return Infer(result, opt, ins, outs, headers);
      });
}

Error InferenceServerHttpClient::AsyncInferMulti(
    OnMultiCompleteFn callback, const std::vector<InferOptions>& options,
    const std::vector<std::vector<InferInput*>>& inputs,
    const std::vector<std::vector<const InferRequestedOutput*>>& outputs,
    const Headers& headers) {
  return multi_detail::AsyncInferMultiImpl(
      std::move(callback), options, inputs, outputs,
      [&](OnCompleteFn cb, const InferOptions& opt, const auto& ins,
          const auto& outs) {
        return AsyncInfer(std::move(cb), opt, ins, outs, headers);
      });
}

}  // namespace client
}  // namespace tc_tpu
