#!/usr/bin/env python3
"""Run the BASELINE.md measurement matrix (rows 1-5) on the current host.

Starts the in-process serving harness (all zoo models, including the
BASELINE models: resnet50, bert_large, ensemble_llama) and measures each
configured row with the perf_analyzer-equivalent or a purpose-built driver.
Writes ``benchmarks/BASELINE_RESULTS.json`` and prints the markdown rows to
paste into BASELINE.md.

Run on the TPU bench host:  python benchmarks/run_baseline.py
Quick CPU smoke:            python benchmarks/run_baseline.py --smoke
"""

from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# sitecustomize pre-imports jax, so the env var alone is ignored (see
# triton_client_tpu/server/__main__.py) — re-apply it
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# v5e peak bf16 matmul throughput, per chip (public spec: 394 TFLOP/s).


def _warm(client, httpclient, model, name, shape, dtype, buckets):
    """One blocking infer per preferred batch bucket so XLA compiles outside
    any measurement window (bench.py learned this the hard way in round 1)."""
    for b in buckets:
        arr = np.zeros((b, *shape), dtype)
        inp = httpclient.InferInput(name, [b, *shape],
                                    {"int32": "INT32", "float32": "FP32"}[arr.dtype.name])
        inp.set_data_from_numpy(arr)
        t0 = time.time()
        client.infer(model, [inp])
        print(f"  warm {model} b={b}: {time.time() - t0:.1f}s", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny windows + tiny llama preset (CPU CI smoke)")
    ap.add_argument("--measure-ms", type=int, default=5000)
    ap.add_argument("--rows", nargs="+", type=int, default=None,
                    help="run only these BASELINE row numbers (default all)")
    args = ap.parse_args()

    def row_on(n):
        return args.rows is None or n in args.rows

    if args.smoke:
        os.environ.setdefault("TRITON_TPU_LLAMA_PRESET", "tiny")
        args.measure_ms = min(args.measure_ms, 1500)

    import triton_client_tpu.http as httpclient
    from triton_client_tpu.models import language, zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    registry = ModelRegistry()
    zoo.register_all(registry)
    harness = ServerHarness(registry)
    harness.start()
    grpc_url = f"127.0.0.1:{harness.grpc_port}"
    results = {}
    t_start = time.time()

    def solo_probe(model, arrays, n=3):
        """Median solo-request latency on an (assumed) idle link."""
        import triton_client_tpu.grpc as pm
        from triton_client_tpu.utils import np_to_triton_dtype

        samples = []
        with pm.InferenceServerClient(grpc_url) as probe:
            for _ in range(n):
                req_inputs = []
                for name, arr in arrays.items():
                    dt = ("BYTES" if arr.dtype == np.object_
                          else np_to_triton_dtype(arr.dtype))
                    inp = pm.InferInput(name, list(arr.shape), dt)
                    inp.set_data_from_numpy(arr)
                    req_inputs.append(inp)
                t0 = time.time()
                probe.infer(model, req_inputs)
                samples.append(time.time() - t0)
        return float(np.median(samples))

    def drain(model, arrays, floor):
        """Block until the abandoned tail of the previous closed-loop level
        has cleared the device link: two consecutive solo probes near the
        PRE-congestion floor (captured before the first level — a floor
        taken from post-congestion samples mistakes "uniformly congested"
        for "drained"; r3 lesson, same fix as bench.py's quiesce)."""
        import triton_client_tpu.grpc as pm
        from triton_client_tpu.utils import np_to_triton_dtype

        with pm.InferenceServerClient(grpc_url) as probe:
            deadline = time.time() + 120.0
            last_two = []
            while time.time() < deadline:
                req_inputs = []
                for name, arr in arrays.items():
                    dt = ("BYTES" if arr.dtype == np.object_
                          else np_to_triton_dtype(arr.dtype))
                    inp = pm.InferInput(name, list(arr.shape), dt)
                    inp.set_data_from_numpy(arr)
                    req_inputs.append(inp)
                t0 = time.time()
                probe.infer(model, req_inputs)
                last_two = (last_two + [time.time() - t0])[-2:]
                if len(last_two) == 2 and max(last_two) < 2.0 * floor:
                    return
                time.sleep(0.3)

    def sweep(model, levels, shm="none", streaming=False, batch=1):
        rows = []
        from triton_client_tpu.perf_analyzer import (_make_data,
                                                     _resolve_model,
                                                     run_level)
        import triton_client_tpu.grpc as pm

        meta = pm.InferenceServerClient(grpc_url)
        inputs, outputs, max_batch = _resolve_model(meta, "grpc", model, "")
        meta.close()
        arrays = _make_data(inputs, {}, batch, max_batch,
                            np.random.default_rng(0))
        floor = solo_probe(model, arrays)
        for level in levels:
            res = run_level("grpc", grpc_url, model, "", level, arrays,
                            outputs, shm, 1 << 22, args.measure_ms / 1000.0,
                            streaming=streaming)
            if res["errors"]:
                print(f"  !! {model} c={level}: {res['errors']} errors: "
                      f"{res['first_error']}", flush=True)
            rows.append(res)
            print(f"  {model} c={level} shm={shm}{' stream' if streaming else ''}: "
                  f"{res['throughput']:.1f} infer/s p50={res['p50_us']/1e3:.1f}ms "
                  f"p99={res['p99_us']/1e3:.1f}ms", flush=True)
            # backlog from this level must not starve the next
            drain(model, arrays, floor)
        best = max(rows, key=lambda r: r["throughput"])
        return {"levels": rows, "best": best}

    # XLA compiles on a tunneled chip can take minutes — warm-up infers must
    # not trip the client's 60s default read timeout.
    warm_client = httpclient.InferenceServerClient(
        harness.http_url, network_timeout=600.0)

    # ---- row 1: simple + system shm --------------------------------------
    if row_on(1):
        print("row 1: simple (system shm)", flush=True)
        results["row1_simple_sysshm"] = sweep("simple", [1, 8], shm="system")

    # ---- row 2: resnet50 over gRPC ---------------------------------------
    if row_on(2):
        print("row 2: resnet50 (async gRPC)", flush=True)
        # concurrency c coalesces into batches the batcher pads to the next
        # preferred bucket — warm every bucket a sweep level can hit, or the
        # measurement window sits behind a fresh XLA compile.
        buckets = [1, 4, 8, 16, 32] if not args.smoke else [1]
        if args.smoke:
            import triton_client_tpu.models.vision as vision
            vision._STAGES = ((1, 8), (1, 8), (1, 8), (1, 8))
        _warm(warm_client, httpclient, "resnet50", "INPUT", (3, 224, 224),
              np.float32, buckets)
        results["row2_resnet50_grpc"] = sweep(
            "resnet50", [1, 4, 8] if not args.smoke else [1])

    # ---- row 3: xla shm on dense_tpu -------------------------------------
    if row_on(3):
        print("row 3: dense_tpu (xla shm)", flush=True)
        _warm(warm_client, httpclient, "dense_tpu", "INPUT", (512,), np.float32,
              [1, 8] if args.smoke else [1, 8, 16, 32, 64])
        results["row3_dense_xlashm"] = sweep("dense_tpu", [1, 8], shm="xla")

    # ---- row 4: bert_large, streaming gRPC + xla shm ---------------------
    if row_on(4):
        print("row 4: bert_large (streaming gRPC)", flush=True)
        if not args.smoke:
            _warm(warm_client, httpclient, "bert_large", "INPUT_IDS",
                  (language.BERT_SEQ_LEN,), np.int32, [1, 2, 4, 8, 16, 32])
            # concurrency must reach max_batch_size (32) for the dynamic
            # batcher to build MFU-deep batches.  WIRE outputs: the MFU
            # number must count device-synchronous completions — xla-shm
            # responses return at dispatch time, so that sweep (kept below
            # as a dispatch/latency metric) overcounts compute ~2x
            # (benchmarks/BERT_PROFILE.md).
            # levels sized to cover the tunnel RTT: with wire outputs each
            # request's completion pays the ~100ms link round trip, so
            # c must be >= device_rate x RTT (~40+) or the closed loop
            # measures the tunnel; deep levels also let the batcher build
            # max_batch=32 executions
            results["row4_bert_stream"] = sweep(
                "bert_large", [32, 64, 128], shm="none", streaming=True)
            best = results["row4_bert_stream"]["best"]
            results["row4_bert_stream"]["mfu"] = language.serving_mfu(
                best["throughput"], language.BERT_LARGE,
                language.BERT_SEQ_LEN, head_cols=language.BERT_HEAD_COLS)
            results["row4_bert_stream"]["tokens_per_sec"] = (
                best["throughput"] * language.BERT_SEQ_LEN)
            # zero-copy response path: NOT an MFU number — demonstrates the
            # xla-shm serving property (responses decoupled from device
            # completion; the shm consumer synchronizes when it reads)
            results["row4_bert_xlashm_dispatch"] = sweep(
                "bert_large", [16], shm="xla", streaming=True)

    # ---- row 5: llama ensemble generation over the stream ----------------
    if row_on(5):
        print("row 5: ensemble_llama sequence/stream generation", flush=True)
        import triton_client_tpu.grpc as grpcclient

        # warm (first token pays compile)
        inp = httpclient.InferInput("TEXT", [1, 1], "BYTES")
        inp.set_data_from_numpy(np.array([[b"warmup"]], dtype=object))
        t0 = time.time()
        warm_client.infer("ensemble_llama", [inp])
        print(f"  warm ensemble_llama: {time.time() - t0:.1f}s", flush=True)

        def gen_loop(seq_id, steps, prompt):
            """Closed-loop stream generation: one request per token, 128-byte
            window, OUT_TEXT appended — the single definition of the protocol
            shared by the serial and concurrent row-5 measurements.  Returns
            (generation wall seconds, per-token latencies); the timed window
            spans first request → last response, excluding client/stream
            setup and teardown (the historical measurement methodology)."""
            done_q: "queue.Queue" = queue.Queue()
            text = prompt
            lats = []
            with grpcclient.InferenceServerClient(grpc_url) as c:
                c.start_stream(
                    callback=lambda result, error: done_q.put((result, error)))
                t_gen = time.time()
                for step in range(steps):
                    ginp = grpcclient.InferInput("TEXT", [1, 1], "BYTES")
                    ginp.set_data_from_numpy(np.array([[text[-128:]]], dtype=object))
                    t0 = time.time()
                    c.async_stream_infer("ensemble_llama", [ginp],
                                         sequence_id=seq_id,
                                         sequence_start=(step == 0),
                                         sequence_end=(step == steps - 1))
                    res, err = done_q.get(timeout=300)
                    if err is not None:
                        raise RuntimeError(err)
                    lats.append(time.time() - t0)
                    text += bytes(
                        np.asarray(res.as_numpy("OUT_TEXT")).reshape(-1)[0])
                wall_s = time.time() - t_gen
                c.stop_stream()
            return wall_s, lats

        gen_steps = 8 if args.smoke else 64
        wall, lat = gen_loop(1, gen_steps, b"In a hole in the ground there lived")
        cfg = language._llama_cfg()
        flops_tok = language.forward_flops_per_token(cfg, language.LLAMA_SEQ_LEN)
        # each generated token re-runs the full 128-token window forward
        window_flops = flops_tok * language.LLAMA_SEQ_LEN
        results["row5_llama_ensemble"] = {
            "preset_params": language.n_params(cfg),
            "gen_tokens": gen_steps,
            "tokens_per_sec": gen_steps / wall,
            "stream_p50_ms": float(np.percentile(lat, 50) * 1e3),
            "stream_p99_ms": float(np.percentile(lat, 99) * 1e3),
            "mfu": (gen_steps / wall) * window_flops / language.V5E_PEAK_FLOPS,
        }
        r5 = results["row5_llama_ensemble"]
        print(f"  llama({r5['preset_params']/1e9:.2f}B params): "
              f"{r5['tokens_per_sec']:.2f} tok/s p50={r5['stream_p50_ms']:.0f}ms "
              f"MFU={r5['mfu']*100:.1f}%", flush=True)

        # concurrent generation: N independent streams; the ensemble's member
        # executions coalesce through llama_tpu's dynamic batcher, so aggregate
        # tokens/sec scales far past the serial per-token RTT floor
        _warm(warm_client, httpclient, "llama_tpu", "TOKENS",
              (language.LLAMA_SEQ_LEN,), np.int32,
              [1, 2, 4, 8] if not args.smoke else [1, 2])
        import threading

        n_streams = 2 if args.smoke else 8
        conc_steps = 4 if args.smoke else 32
        worker_errors = []
        t_conc = time.time()

        def guarded_worker(widx):
            try:
                gen_loop(2000 + widx, conc_steps,
                         f"stream {widx}: in the beginning".encode())
            except Exception as exc:  # noqa: BLE001 — surfaced after join
                worker_errors.append((widx, exc))

        threads = [threading.Thread(target=guarded_worker, args=(w,), daemon=True)
                   for w in range(n_streams)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=600)
        if worker_errors:
            raise RuntimeError(f"concurrent-stream workers failed: {worker_errors}")
        if any(t.is_alive() for t in threads):
            raise RuntimeError("concurrent-stream worker hung past 600s join")
        conc_wall = time.time() - t_conc
        # every worker completed exactly conc_steps tokens (guards above raise
        # on any failure or hang)
        total_toks = n_streams * conc_steps
        results["row5_llama_concurrent"] = {
            "streams": n_streams,
            "gen_tokens": total_toks,
            "tokens_per_sec": total_toks / conc_wall,
            "mfu": (total_toks / conc_wall) * window_flops / language.V5E_PEAK_FLOPS,
        }
        r5c = results["row5_llama_concurrent"]
        print(f"  llama concurrent x{n_streams}: {r5c['tokens_per_sec']:.2f} "
              f"tok/s aggregate MFU={r5c['mfu']*100:.1f}%", flush=True)

    warm_client.close()
    harness.stop()
    # per-row provenance: RTT varies 70-145 ms across tunnel sessions, so
    # every row records which session measured it (partial --rows runs
    # merge into the file without masquerading as one session)
    session = {
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "backend": os.environ.get("JAX_PLATFORMS", "default"),
        "session_wall_s": round(time.time() - t_start, 1),
    }
    for key, val in results.items():
        if isinstance(val, dict):
            val["session"] = session
    results["wall_s"] = time.time() - t_start
    results["backend"] = os.environ.get("JAX_PLATFORMS", "default")

    # smoke output must never clobber a real TPU measurement (same
    # convention as run_decode_bench.py)
    name = ("BASELINE_RESULTS_SMOKE.json" if args.smoke
            else "BASELINE_RESULTS.json")
    out = os.path.join(REPO, "benchmarks", name)
    if args.rows is not None and os.path.exists(out):
        # partial run: merge over the existing matrix, don't clobber rows
        # that weren't measured
        with open(out) as f:
            merged = json.load(f)
        merged.update(results)
        results = merged
    with open(out, "w") as f:
        json.dump(results, f, indent=2)
    print(f"\nwrote {out}")

    # markdown rows for BASELINE.md
    def fmt(r):
        b = r["best"]
        return (f"{b['throughput']:.1f} infer/s, p50 {b['p50_us']/1e3:.1f} ms, "
                f"p99 {b['p99_us']/1e3:.1f} ms (c={b['concurrency']})")

    print("\n--- BASELINE.md rows ---")
    if "row1_simple_sysshm" in results:
        print(f"| 1 | simple, system shm | "
              f"{fmt(results['row1_simple_sysshm'])} |")
    if "row2_resnet50_grpc" in results:
        print(f"| 2 | resnet50, async gRPC | "
              f"{fmt(results['row2_resnet50_grpc'])} |")
    if "row3_dense_xlashm" in results:
        print(f"| 3 | dense_tpu, xla shm | "
              f"{fmt(results['row3_dense_xlashm'])} |")
    if "row4_bert_stream" in results:
        r4 = results["row4_bert_stream"]
        print(f"| 4 | bert_large, streaming gRPC (wire) | {fmt(r4)}, "
              f"{r4['tokens_per_sec']:.0f} tok/s, MFU {r4['mfu']*100:.1f}% |")
    if "row4_bert_xlashm_dispatch" in results:
        r4d = results["row4_bert_xlashm_dispatch"]["best"]
        print(f"| 4b | bert_large xla-shm zero-copy response rate "
              f"(dispatch, NOT MFU) | {r4d['throughput']:.1f} resp/s, "
              f"p50 {r4d['p50_us']/1e3:.1f} ms |")
    if ("row5_llama_ensemble" in results
            and "row5_llama_concurrent" in results):
        r5 = results["row5_llama_ensemble"]
        r5c = results["row5_llama_concurrent"]
        print(f"| 5 | ensemble_llama stream gen | "
              f"{r5['tokens_per_sec']:.2f} tok/s, "
              f"stream p50 {r5['stream_p50_ms']:.0f} ms, "
              f"MFU {r5['mfu']*100:.1f}%; "
              f"x{r5c['streams']} streams: {r5c['tokens_per_sec']:.2f} "
              f"tok/s, MFU {r5c['mfu']*100:.1f}% |")
    return 0


if __name__ == "__main__":
    sys.exit(main())
