#!/usr/bin/env python3
"""BASELINE row 7: KV-cache incremental decode (`llama_decode`).

Measures closed-loop generation over a gRPC sequence stream — serial
(tok/s, ms/token) and N concurrent streams (aggregate tok/s) — against the
in-process harness, same methodology as rows 1-5 (benchmarks/run_baseline.py).

    python benchmarks/run_decode_bench.py            # full (TPU: 1b preset)
    python benchmarks/run_decode_bench.py --smoke    # CPU CI smoke
"""

import argparse
import json
import os
import queue
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# sitecustomize pre-imports jax, so the env var alone is ignored (see
# triton_client_tpu/server/__main__.py) — re-apply it
if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def gen_loop(grpc_url, grpcclient, S, seq_id, prompt, steps):
    """Prefill once, then feed each produced token back as a [1] step."""
    done: "queue.Queue" = queue.Queue()
    lats = []
    with grpcclient.InferenceServerClient(grpc_url) as c:
        c.start_stream(callback=lambda result, error: done.put((result, error)))
        win = np.zeros(S, np.int32)
        b = np.frombuffer(prompt[-S:], np.uint8)
        win[S - len(b):] = b
        inp = grpcclient.InferInput("TOKENS", [S], "INT32")
        inp.set_data_from_numpy(win)
        c.async_stream_infer("llama_decode", [inp], sequence_id=seq_id,
                             sequence_start=True)
        res, err = done.get(timeout=2400)
        if err is not None:
            raise RuntimeError(err)
        for i in range(steps):
            tok = np.asarray(res.as_numpy("NEXT_TOKEN")).astype(
                np.int32).reshape(1)
            ninp = grpcclient.InferInput("TOKENS", [1], "INT32")
            ninp.set_data_from_numpy(tok)
            t0 = time.time()
            c.async_stream_infer("llama_decode", [ninp], sequence_id=seq_id,
                                 sequence_end=(i == steps - 1))
            res, err = done.get(timeout=1200)
            if err is not None:
                raise RuntimeError(err)
            lats.append(time.time() - t0)
        c.stop_stream()
    return lats


def measure_mode(mode, args, slots, chunk):
    """One harness per decode mode (DecodeModel reads the env at init)."""
    os.environ["TRITON_TPU_DECODE_MODE"] = mode
    os.environ["TRITON_TPU_DECODE_SLOTS"] = str(slots)
    os.environ["TRITON_TPU_PREFILL_CHUNK"] = str(chunk if mode == "batched"
                                                 else 0)
    from triton_client_tpu.models import language, zoo
    from triton_client_tpu.server.registry import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness
    import triton_client_tpu.grpc as grpcclient

    registry = ModelRegistry()
    zoo.register_all(registry)
    h = ServerHarness(registry)
    h.start()
    S = language.LLAMA_SEQ_LEN
    out = {"mode": mode, "slots": slots,
           "prefill_chunk": chunk if mode == "batched" else 0}
    try:
        # serial (first sequence pays prefill+step compiles; timing uses
        # per-step latencies, not the compile)
        steps = 4 if args.smoke else 24
        gen_loop(h.grpc_url, grpcclient, S, 700,
                 b"In a hole in the ground there lived", steps)
        lats = gen_loop(h.grpc_url, grpcclient, S, 701,
                        b"It was the best of times", steps)  # warm pass
        out["serial"] = {
            "tokens_per_sec": 1.0 / float(np.mean(lats)),
            "ms_per_token_p50": float(np.percentile(lats, 50) * 1e3),
        }
        print(f"[{mode}] serial: "
              f"{out['serial']['tokens_per_sec']:.2f} tok/s, p50 "
              f"{out['serial']['ms_per_token_p50']:.0f} ms/token",
              flush=True)

        conc_steps = 4 if args.smoke else 16
        out["concurrent"] = []
        for n_streams in args.streams:
            if n_streams > slots and mode == "batched":
                # starts beyond the slot pool are rejected; skip
                continue
            errors = []

            def worker(w):
                try:
                    gen_loop(h.grpc_url, grpcclient, S, 800 + w,
                             f"stream {w}: in the beginning".encode(),
                             conc_steps)
                except Exception as exc:  # noqa: BLE001 — after join
                    errors.append((w, exc))

            t0 = time.time()
            threads = [threading.Thread(target=worker, args=(w,),
                                        daemon=True)
                       for w in range(n_streams)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=2400)
            if errors:
                raise RuntimeError(f"decode workers failed: {errors}")
            if any(t.is_alive() for t in threads):
                raise RuntimeError("decode worker hung")
            wall = time.time() - t0
            total = n_streams * (conc_steps + 1)  # +1 = prefill's token
            out["concurrent"].append(
                {"streams": n_streams, "tokens_per_sec": total / wall})
            print(f"[{mode}] x{n_streams} streams: {total / wall:.1f} "
                  f"tok/s aggregate", flush=True)
    finally:
        h.stop()
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny preset + short loops (CPU CI)")
    ap.add_argument("--modes", nargs="+",
                    default=["independent"],
                    choices=["independent", "batched"],
                    help="decode modes to sweep. Default sweeps only "
                    "'independent': with a client RTT inside the closed "
                    "loop, a batched tick is a per-cohort sync point and "
                    "measures 10-20%% behind (BASELINE row 7) — batched is "
                    "the server-side-generation architecture (row 15) and "
                    "the prefill-contended genai-perf workload's winner "
                    "(row 8); pass --modes independent batched to compare")
    ap.add_argument("--streams", nargs="+", type=int, default=None,
                    help="concurrency sweep (default 8 16 32; smoke: 2)")
    ap.add_argument("--slots", type=int, default=32,
                    help="decode slots for batched mode")
    ap.add_argument("--chunk", type=int, default=32,
                    help="prefill chunk tokens for batched mode (0=off)")
    args = ap.parse_args()
    if args.smoke:
        os.environ.setdefault("TRITON_TPU_LLAMA_PRESET", "tiny")
        if args.streams is None:
            args.streams = [2]
        args.slots = min(args.slots, 4)
    elif args.streams is None:
        args.streams = [8, 16, 32]

    results = {"sweep": [measure_mode(m, args, args.slots, args.chunk)
                         for m in args.modes]}
    # smoke output must never clobber a real TPU measurement
    name = "DECODE_RESULTS_SMOKE.json" if args.smoke else "DECODE_RESULTS.json"
    out = os.path.join(os.path.dirname(os.path.abspath(__file__)), name)
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(f"wrote {out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
