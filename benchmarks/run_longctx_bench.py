#!/usr/bin/env python3
"""BASELINE row 6/11: long-context document scorer latency over HTTP.

Measures ``longctx_tpu`` p50 at the active preset's sequence length with the
pallas flash kernel, and (optionally) with XLA fused attention for the same
request (``--compare-xla`` restarts the harness with TRITON_TPU_FLASH=0 —
the kernel choice binds at trace time).

    TRITON_TPU_LONGCTX_PRESET=xl python benchmarks/run_longctx_bench.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])


def measure(n: int = 8) -> dict:
    import triton_client_tpu.http as httpclient
    from triton_client_tpu.models import language, zoo
    from triton_client_tpu.server import ModelRegistry
    from triton_client_tpu.server.testing import ServerHarness

    registry = ModelRegistry()
    zoo.register_all(registry)
    S = language.longctx_seq_len()
    with ServerHarness(registry) as h:
        with httpclient.InferenceServerClient(h.http_url) as c:
            toks = np.random.default_rng(0).integers(
                0, 255, (1, S), dtype=np.int32)
            inp = httpclient.InferInput("TOKENS", [1, S], "INT32")
            inp.set_data_from_numpy(toks)
            c.infer("longctx_tpu", [inp])  # compile outside the clock
            lats = []
            for _ in range(n):
                t0 = time.perf_counter()
                c.infer("longctx_tpu", [inp])
                lats.append(time.perf_counter() - t0)
    return {
        "seq_len": S,
        "flash": os.environ.get("TRITON_TPU_FLASH", "1") != "0",
        "p50_ms": round(float(np.percentile(lats, 50)) * 1e3, 1),
        "min_ms": round(float(np.min(lats)) * 1e3, 1),
    }


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("-n", type=int, default=8)
    parser.add_argument("--compare-xla", action="store_true",
                        help="also measure with TRITON_TPU_FLASH=0 in a "
                        "subprocess (kernel choice binds at trace time)")
    args = parser.parse_args()

    print(json.dumps(measure(args.n)))
    if args.compare_xla:
        import subprocess

        env = dict(os.environ)
        env["TRITON_TPU_FLASH"] = "0"
        subprocess.run(
            [sys.executable, os.path.abspath(__file__), "-n", str(args.n)],
            env=env, check=True)


if __name__ == "__main__":
    main()
