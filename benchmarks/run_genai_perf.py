#!/usr/bin/env python3
"""Measure the genai-perf metric set against the live ``llama_decode`` model.

Run on the TPU bench host (defaults) or CPU (JAX_PLATFORMS=cpu).  Prints the
full report per concurrency level; the aggregate numbers extend BASELINE.md
row 7 with TTFT/ITL percentiles.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "JAX_PLATFORMS" in os.environ:
    import jax

    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from triton_client_tpu import genai_perf
from triton_client_tpu.models import zoo
from triton_client_tpu.server import ModelRegistry
from triton_client_tpu.server.testing import ServerHarness


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--concurrency", default="1,8",
                        help="comma-separated levels")
    parser.add_argument("--output-tokens", type=int, default=16)
    parser.add_argument("--num-requests", type=int, default=8)
    parser.add_argument("--model", default="llama_decode")
    parser.add_argument("--generate-model", default="llama_generate",
                        help="model for the generate_stream (SSE) sweep")
    args = parser.parse_args()

    registry = ModelRegistry()
    zoo.register_all(registry)
    with ServerHarness(registry) as h:
        # warm: one generation outside the clock (XLA prefill+step compile)
        genai_perf.profile(h.grpc_url, args.model, concurrency=1,
                           output_tokens=1, num_requests=1)
        for level in [int(c) for c in args.concurrency.split(",")]:
            report = genai_perf.profile(
                h.grpc_url, args.model, concurrency=level,
                output_tokens=args.output_tokens,
                num_requests=max(args.num_requests, level))
            print(json.dumps(report))
        # server-side loop over the generate extension (SSE): ITL here is
        # on-device step time, not a client round trip per token.  Its own
        # warm pass: the generate path compiles the independent prefill/step
        # pair, which the decode warm-up above only covers in independent
        # decode mode.
        # output_tokens=2 so the decode `step` compiles too (a 1-token
        # generation is prefill-only)
        genai_perf.profile_generate(
            h.http_url, args.generate_model, concurrency=1,
            output_tokens=2, num_requests=1)
        for level in [int(c) for c in args.concurrency.split(",")]:
            report = genai_perf.profile_generate(
                h.http_url, args.generate_model, concurrency=level,
                output_tokens=args.output_tokens,
                num_requests=max(args.num_requests, level))
            print(json.dumps(report))


if __name__ == "__main__":
    main()
